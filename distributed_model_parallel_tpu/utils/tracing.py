"""Span tracing: causal host timelines on the telemetry stream.

The telemetry layer records *points* (step/epoch/failure records) and
*aggregates* (counters, histograms) — but nothing says what a run was
doing *between* the points, or which phase inside a step/request/round
the wall time went to. This module is the missing interval primitive:

* :func:`span` — a ``with``-statement context manager (usable as a
  decorator) that measures one named interval with ``time.monotonic()``
  (NTP-immune durations; the record's wall-clock ``t0``/``ts`` stay on
  ``time.time()`` for cross-stream correlation) and writes one typed
  ``span`` record onto the thread's bound :class:`~.telemetry.TelemetryRun`;
* a **thread-local span stack**: spans opened inside spans record their
  parent id and depth, so the trainer's ``train_epoch`` > ``drain`` >
  ``checkpoint_save`` nesting is explicit in the stream and renders as
  nested bars in the Chrome-trace export (``scripts/dmp_trace.py``);
* :func:`install` / :func:`sink_scope` — per-thread sink binding. The
  trainers install their run stream at construction (so resume/restore
  spans land too); the serving engine and orchestrator bind theirs for
  the scope of a run/round. Thread-local binding is what makes this
  tenant-correct: the orchestrator runs each tenant's trainer on its own
  thread inside a ``tenant_scope``, so every span lands on that tenant's
  stream and inherits its ``tenant`` tag without the instrumentation
  sites knowing tenancy exists.

Overhead contract: with no sink bound (or tracing disabled via
``DMP_TRACING=0`` / :func:`set_enabled`) a span is a no-op — two
attribute reads, no allocation, no clock call. With a sink bound the
cost is one JSONL append per span; instrumentation sites are chosen at
window/epoch/round granularity (never inside the async dispatch hot
loop), and tests/test_tracing.py asserts the measured per-span cost
stays under 2% of the CPU perf smoke's p50 step time.

Record schema (see docs/TRACING.md and the OBSERVABILITY.md record
table): ``{kind: "span", name, t0, dur_s, sid, parent, depth, thread,
**attrs}`` where ``t0`` is the wall-clock start (unix seconds), ``ts``
(stamped by TelemetryRun at write) the wall-clock end, and ``dur_s`` the
monotonic-clock duration.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading
import time
from typing import Any

__all__ = [
    "enabled",
    "install",
    "installed",
    "live_spans",
    "new_trace_id",
    "record_span",
    "rtrace",
    "set_enabled",
    "sink_scope",
    "span",
    "uninstall",
]

_state = threading.local()
_ids = itertools.count(1)       # process-unique span ids (GIL-atomic)
_enabled = os.environ.get("DMP_TRACING", "1") != "0"

# Every thread's live span stack, by thread ident — the statusz
# exporter's "what is each thread doing right now" view and the crash
# flight recorder's span context. The stack LISTS are shared with the
# thread-locals (mutated in place by span enter/exit), so reads here see
# the live state; registration happens once per thread.
_live_lock = threading.Lock()
_live_stacks: dict[int, tuple[str, list]] = {}


def live_spans() -> dict[str, list[str]]:
    """The open span stack of every live thread, outermost first:
    ``{thread_name: [span names]}``. Threads with no open span are
    omitted; stacks of dead threads are pruned. Snapshot semantics — the
    lists are copied, concurrent span exits cannot mutate the result."""
    alive = {t.ident: t for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    with _live_lock:
        for ident in list(_live_stacks):
            if ident not in alive:
                del _live_stacks[ident]
                continue
            name, stack = _live_stacks[ident]
            if stack:
                out[name] = [s[1] for s in list(stack)]
    return out


def enabled() -> bool:
    """Is span recording globally enabled (``DMP_TRACING``, default on)?
    A disabled process still *runs* every instrumented site — spans just
    skip the stack push and the record write."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip span recording process-wide (the on/off lever the overhead
    comparison in tests/test_tracing.py uses)."""
    global _enabled
    _enabled = bool(on)


def install(sink) -> None:
    """Bind ``sink`` (a :class:`~.telemetry.TelemetryRun`, or anything
    with ``.record(kind, **fields)``) as THIS thread's span sink. The
    trainers call this at construction with their run stream; a later
    install on the same thread replaces the binding (last trainer wins —
    exactly the stream the thread is currently writing)."""
    _state.sink = sink


def installed():
    """This thread's bound span sink (None when spans are dropped)."""
    return getattr(_state, "sink", None)


def uninstall() -> None:
    _state.sink = None


class sink_scope:
    """Bind a sink for a scope, restoring the previous binding on exit:
    ``with tracing.sink_scope(run): ...``. A ``None`` sink leaves the
    current binding in place (the serving engine runs with or without a
    telemetry stream attached)."""

    def __init__(self, sink):
        self.sink = sink
        self._prev = None

    def __enter__(self):
        if self.sink is not None:
            self._prev = installed()
            install(self.sink)
        return self

    def __exit__(self, *exc):
        if self.sink is not None:
            install(self._prev)
        return False


def _stack() -> list:
    st = getattr(_state, "stack", None)
    if st is None:
        st = _state.stack = []
        t = threading.current_thread()
        with _live_lock:
            _live_stacks[t.ident] = (t.name, st)
    return st


def record_span(name: str, dur_s: float, *, t0: float | None = None,
                sink=None, **attrs: Any) -> None:
    """Imperative form: write one ``span`` record for an interval timed
    by the caller (sites where the interval already exists as a number
    and wrapping the work in a context manager would restructure it).
    Parent/depth come from the thread's live span stack, so imperative
    spans nest under whatever ``with span(...)`` is open."""
    sink = sink if sink is not None else installed()
    if sink is None or not _enabled:
        return
    st = _stack()
    parent = st[-1][0] if st else None
    try:
        sink.record("span", name=name,
                    t0=t0 if t0 is not None else time.time() - dur_s,
                    dur_s=dur_s, sid=next(_ids), parent=parent,
                    depth=len(st),
                    thread=threading.current_thread().name, **attrs)
    except Exception:
        # A stale/unwritable sink must not take down the recording site:
        # spans are observability, not control flow.
        pass


_trace_ids = itertools.count(1)   # per-process request trace ids


def new_trace_id() -> str:
    """A process-unique request trace id (``rtrace`` records carry it as
    ``trace``). Stamped once per request at admission into the serving
    tier — the identity that survives queueing, migration between
    replicas, and brownout clamps (docs/TRACING.md "Request tracing")."""
    return f"{os.getpid():x}-{next(_trace_ids):x}"


def rtrace(req, event: str, *, sink=None, **fields: Any) -> None:
    """Write one typed ``rtrace`` record for a request-scoped event.

    ``req`` is any object carrying ``trace_id`` (str | None), ``trace_seq``
    (int) and ``rid`` — in practice serve/scheduler.py's ``Request``. The
    per-request sequence number is incremented HERE, under the emitting
    thread, so a request's records are causally ordered by ``seq`` even
    when wall-clock ``ts`` ties (two events inside one engine iteration)
    or skews across streams. Because the Request OBJECT migrates between
    replicas (export/import moves KV pages by value, not the request),
    ``seq`` stays monotonic across the hop — the joiner links the two
    stream segments by ``(trace, seq)`` adjacency.

    No-op when the request was never stamped (``trace_id`` is None — an
    engine without telemetry) or no sink resolves; never raises (tracing
    is observability, not control flow)."""
    trace = getattr(req, "trace_id", None)
    if trace is None:
        return
    sink = sink if sink is not None else installed()
    if sink is None:
        return
    req.trace_seq += 1
    try:
        sink.record("rtrace", trace=trace, seq=req.trace_seq,
                    request=req.rid, event=event, **fields)
    except Exception:
        pass


class span:
    """``with span("drain", n=3): ...`` — or ``@span("evaluate")`` as a
    decorator (each call gets its own span). Attributes land on the
    record; :meth:`annotate` adds more from inside the body. An
    exception inside the span still writes the record, with
    ``error=<ExceptionType>`` — a timeline that loses its crashing span
    hides exactly the interval being debugged."""

    __slots__ = ("name", "attrs", "_sink", "_sid", "_parent", "_depth",
                 "_t0m", "_t0w")

    def __init__(self, name: str, **attrs: Any):
        self.name = name
        self.attrs = attrs
        self._sink = None

    def __enter__(self):
        sink = installed()
        if sink is None or not _enabled:
            self._sink = None
            return self
        self._sink = sink
        st = _stack()
        self._parent = st[-1][0] if st else None
        self._depth = len(st)
        self._sid = next(_ids)
        st.append((self._sid, self.name))
        self._t0w = time.time()
        self._t0m = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._sink is None:
            return False
        dur = time.monotonic() - self._t0m
        st = _stack()
        # Pop our own frame; a mispaired stack (a site that leaked spans
        # across threads) must not corrupt later spans' parents.
        while st and st[-1][0] != self._sid:
            st.pop()
        if st:
            st.pop()
        fields = dict(self.attrs)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        try:
            self._sink.record("span", name=self.name, t0=self._t0w,
                              dur_s=dur, sid=self._sid, parent=self._parent,
                              depth=self._depth,
                              thread=threading.current_thread().name,
                              **fields)
        except Exception:
            # A full disk / closed stream must not take down the traced
            # run: spans are observability, not control flow.
            pass
        self._sink = None
        return False

    def annotate(self, **attrs: Any) -> None:
        """Add attributes from inside the body (values computed by the
        spanned work itself, e.g. how many batches a drain folded)."""
        self.attrs.update(attrs)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(self.name, **self.attrs):
                return fn(*args, **kwargs)
        return wrapped
