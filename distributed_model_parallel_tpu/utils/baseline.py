"""Cross-run performance baseline ledger + regression gate.

The perf trajectory sat flat at MFU ~0.08 through BENCH r01-r04 and no
machine noticed, because every artifact was judged in isolation. This
module gives the repo a memory: a **JSONL ledger** of headline metrics
per ``(metric, plan-payload)`` key — seeded from the checked-in
``BENCH_*.json`` / ``MULTICHIP_*.json`` artifacts and appended by green
runs — and a **gate** that compares a fresh run against the ledger's
recent history with a noise band, so a slowdown fails loudly instead of
shipping as the new normal.

Gate policy (docs/TRACING.md "The regression gate"):

* tracked metrics: ``throughput`` (samples/s/chip or tokens/s/chip —
  the bench headline ``value``), ``mfu``, ``ttft_p99_s``,
  ``token_latency_p99_s``, ``step_time_p50_s`` (p50 over the stream's
  ``step`` records);
* baseline = the **green** ledger entries sharing the fresh run's key
  (same headline metric AND the same parallel plan payload — a dp4
  number must never gate a dp8 run; entries predating plan embedding
  match by metric name alone);
* noise band = median ± ``k``·(1.4826·MAD) over the last ``history``
  green values, floored at ``rel_floor``·|median| (a short or perfectly
  repeatable history has MAD 0 — without the floor every run would trip
  on measurement jitter);
* regression = worse than the band edge in the metric's bad direction
  (lower throughput/MFU, higher latency). No baseline for a key means
  no verdict — the gate reports it and passes (you cannot regress
  against nothing);
* every verdict is written as ONE typed ``gate`` telemetry record, and
  a flagged regression carries an **attribution**: the span (or
  step-phase) whose share of the run's time grew most vs the baseline
  entry — the "where to look first" pointer (utils/tracing.py).

``scripts/dmp_gate.py`` is the CLI; ``bench.py`` runs the gate
automatically after every headline measurement (warn-only by default,
``DMP_BENCH_GATE=strict`` exits nonzero).
"""

from __future__ import annotations

import json
import os
import time
from statistics import median
from typing import Any, Iterable

__all__ = [
    "DEFAULT_K",
    "DEFAULT_REL_FLOOR",
    "GATE_METRICS",
    "append_entries",
    "emit_gate_record",
    "entries_from_points",
    "entry_key",
    "extract_points",
    "gate_points",
    "ingest_artifact",
    "load_ledger",
    "phase_shares",
    "span_shares",
]

# metric name -> True when higher is better.
GATE_METRICS: dict[str, bool] = {
    "throughput": True,
    "mfu": True,
    "ttft_p99_s": False,
    "token_latency_p99_s": False,
    "step_time_p50_s": False,
    # Serving-efficiency fields (BENCH_serve chat mode): a prefix-cache
    # or proposer regression can hide inside an unchanged tokens/s on a
    # faster machine — gate the ratios directly.
    "cache_hit_rate": True,
    "draft_accept_rate": True,
    # Fleet-mode serve drill (BENCH_serve with DMP_BENCH_SERVE_FLEET):
    # the headline value is fleet tokens/s/chip (-> throughput above);
    # these cover the self-healing half. post_kill_ttft_p99_s is the
    # admission latency after a replica kill — the number the whole
    # migration machinery exists to hold down. migrations gates
    # higher-better: a drop below the band means the drill stopped
    # actually migrating (requests restarting from scratch, or the kill
    # not landing mid-stream anymore).
    "post_kill_ttft_p99_s": False,
    "migrations": True,
    # Overload drill (BENCH_serve overload mode): goodput is tokens/s
    # of requests completed WITHIN deadline under 2x offered load — the
    # number the whole shedding/brownout plane exists to hold up; the
    # shed fraction gates lower-better so a drifting admission path
    # (shedding more than the band needs) fails loudly even when
    # goodput holds.
    "goodput_tokens_per_s": True,
    "shed_fraction": False,
    # Crash drill (BENCH_serve fleet mode with a journal): cumulative
    # journal recovery-pass seconds — the time accepted requests sat
    # unservable between a hard crash and their replay re-admission.
    # Lower-better: a creeping recovery pass is exactly the regression
    # the write-ahead journal exists to bound.
    "recovery_time_s": False,
}

DEFAULT_K = 3.0
DEFAULT_REL_FLOOR = 0.05
DEFAULT_HISTORY = 8


def _canon_plan(plan: Any) -> str:
    return json.dumps(plan, sort_keys=True) if plan else ""


def entry_key(metric: str, plan: Any) -> str:
    """The ledger key: headline metric name + canonicalized plan payload
    (autotune/plan.plan_payload — strategy + axis degrees). Two runs
    compare only when they measured the same thing on the same layout."""
    canon = _canon_plan(plan)
    return f"{metric}|{canon}" if canon else str(metric)


# ---------------------------------------------------------------------------
# Ledger I/O
# ---------------------------------------------------------------------------

def load_ledger(path: str) -> list[dict]:
    """All ledger entries, oldest first; ``[]`` when the file does not
    exist yet. Torn lines are skipped with the same warning counter as
    any telemetry stream (a ledger is itself an append-only JSONL
    stream a killed run may tear)."""
    from distributed_model_parallel_tpu.utils.telemetry import read_records

    try:
        return read_records(path)
    except FileNotFoundError:
        return []


def append_entries(path: str, entries: Iterable[dict]) -> int:
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    n = 0
    with open(path, "a") as f:
        for e in entries:
            f.write(json.dumps(e, sort_keys=True) + "\n")
            n += 1
    return n


# ---------------------------------------------------------------------------
# Seeding: the checked-in BENCH_*.json / MULTICHIP_*.json artifacts
# ---------------------------------------------------------------------------

def ingest_artifact(path: str) -> list[dict]:
    """Ledger entries from one committed bench artifact.

    * a BENCH artifact with a ``parsed`` headline record becomes a green
      entry keyed by its metric (+plan when embedded — r01-r05 predate
      plan embedding and match by metric name);
    * a failed artifact (``rc != 0`` / no measurement) becomes a
      **non-green** entry: the hole in the trajectory is recorded, never
      used as a baseline;
    * a MULTICHIP dry-run artifact (no headline number) becomes a
      presence entry keyed ``multichip`` with its ``ok`` verdict.
    """
    with open(path) as f:
        data = json.load(f)
    source = os.path.basename(path)
    ts = os.path.getmtime(path)
    if "n_devices" in data and "parsed" not in data:     # MULTICHIP dryrun
        return [{
            "ts": ts, "key": "multichip", "metric": "multichip",
            "workload": "multichip", "unit": None, "plan": None,
            "green": bool(data.get("ok")) and data.get("rc", 1) == 0,
            "source": source, "metrics": {},
        }]
    parsed = data.get("parsed") or {}
    value = parsed.get("value")
    if data.get("rc", 0) != 0 or value is None:
        return [{
            "ts": ts, "key": "bench-failure", "metric": parsed.get("metric"),
            "workload": None, "unit": parsed.get("unit"), "plan": None,
            "green": False, "source": source,
            "metrics": {}, "error": parsed.get("error", f"rc {data.get('rc')}"),
        }]
    metrics: dict[str, float] = {"throughput": float(value)}
    for src, dst in (("mfu", "mfu"), ("ttft_p99_s", "ttft_p99_s"),
                     ("token_latency_p99_s", "token_latency_p99_s"),
                     ("cache_hit_rate", "cache_hit_rate"),
                     ("draft_accept_rate", "draft_accept_rate"),
                     ("post_kill_ttft_p99_s", "post_kill_ttft_p99_s"),
                     ("migrations", "migrations"),
                     ("goodput_tokens_per_s", "goodput_tokens_per_s"),
                     ("shed_fraction", "shed_fraction"),
                     ("recovery_time_s", "recovery_time_s")):
        v = parsed.get(src)
        if isinstance(v, (int, float)):
            metrics[dst] = float(v)
    plan = parsed.get("plan")
    phases = (parsed.get("step_phase") or {}).get("phases")
    return [{
        "ts": ts, "key": entry_key(parsed["metric"], plan),
        "metric": parsed["metric"], "workload": None,
        "unit": parsed.get("unit"), "plan": plan, "green": True,
        "source": source, "metrics": metrics,
        "phases": phases if phases else None,
    }]


# ---------------------------------------------------------------------------
# Fresh-run extraction
# ---------------------------------------------------------------------------

def span_shares(records: list[dict]) -> dict[str, float] | None:
    """Per-span-name share of total span time over a stream — the
    fingerprint the gate diffs to say WHICH phase grew. All spans count
    (shares are of the summed span time, parents and children alike), so
    a child span growing shows up even when its parent absorbs it."""
    totals: dict[str, float] = {}
    for r in records:
        if r.get("kind") != "span":
            continue
        d = r.get("dur_s")
        if isinstance(d, (int, float)):
            totals[str(r.get("name"))] = totals.get(str(r.get("name")),
                                                    0.0) + float(d)
    s = sum(totals.values())
    if s <= 0:
        return None
    return {k: v / s for k, v in sorted(totals.items())}


def phase_shares(phases: dict | None) -> dict[str, float] | None:
    """Shares over a ``step_phase`` record's ``*_s`` keys."""
    if not phases:
        return None
    vals = {k: float(v) for k, v in phases.items()
            if k.endswith("_s") and isinstance(v, (int, float))}
    s = sum(vals.values())
    if s <= 0:
        return None
    return {k: v / s for k, v in sorted(vals.items())}


def _median_of(xs: list[float]) -> float | None:
    return median(xs) if xs else None


def extract_points(records: list[dict]) -> list[dict]:
    """Headline measurement points from a telemetry stream.

    Every ``bench`` record becomes one point (keyed by its metric +
    embedded plan). A stream without bench records (a trainer run)
    yields one point keyed by its ``run_start`` run name + mesh — so the
    gate also works on plain training streams, not only bench ones.
    Each point carries the stream-level ``step_time_p50_s`` and the
    span/phase share fingerprints for attribution.
    """
    by_kind: dict[str, list[dict]] = {}
    for r in records:
        by_kind.setdefault(str(r.get("kind")), []).append(r)
    step_times = [r["step_time_s"] for r in by_kind.get("step", [])
                  if isinstance(r.get("step_time_s"), (int, float))]
    samples = [r["samples_per_s"] for r in by_kind.get("step", [])
               if isinstance(r.get("samples_per_s"), (int, float))]
    tokens = [r["tokens_per_s"] for r in by_kind.get("step", [])
              if isinstance(r.get("tokens_per_s"), (int, float))]
    # A stream carrying BOTH units (a fleet merge of CNN + LM tenants)
    # has no single throughput number — a median over a mixed-unit pool
    # would be a meaningless baseline, so the fallback point then gates
    # on step time only.
    thr_samples = (samples if samples and not tokens
                   else tokens if tokens and not samples else [])
    step_p50 = _median_of(step_times)
    spans = span_shares(records)
    last_phase = (by_kind.get("step_phase") or [{}])[-1].get("phases")
    points: list[dict] = []
    for b in by_kind.get("bench", []):
        if b.get("value") is None:
            continue
        metrics: dict[str, float] = {"throughput": float(b["value"])}
        for k in ("mfu", "ttft_p99_s", "token_latency_p99_s",
                  "cache_hit_rate", "draft_accept_rate",
                  "post_kill_ttft_p99_s", "migrations",
                  "goodput_tokens_per_s", "shed_fraction",
                  "recovery_time_s"):
            if isinstance(b.get(k), (int, float)):
                metrics[k] = float(b[k])
        if step_p50 is not None:
            metrics["step_time_p50_s"] = step_p50
        points.append({
            "metric": b.get("metric"), "unit": b.get("unit"),
            "plan": b.get("plan"),
            "key": entry_key(b.get("metric"), b.get("plan")),
            "metrics": metrics, "span_shares": spans,
            "phases": (b.get("step_phase") or {}).get("phases")
            or last_phase,
        })
    if not points and (step_p50 is not None or thr_samples):
        start = (by_kind.get("run_start") or [{}])[-1]
        meta = start.get("meta") or {}
        metric = (f"run_{start.get('run', 'unknown')}"
                  f"_{meta.get('workload', 'unknown')}")
        metrics = {}
        if step_p50 is not None:
            metrics["step_time_p50_s"] = step_p50
        m = _median_of(sorted(thr_samples))
        if m is not None:
            metrics["throughput"] = m
        points.append({
            "metric": metric, "unit": None,
            "plan": {"mesh": meta.get("mesh")} if meta.get("mesh") else None,
            "key": entry_key(metric,
                             {"mesh": meta.get("mesh")}
                             if meta.get("mesh") else None),
            "metrics": metrics, "span_shares": spans, "phases": last_phase,
        })
    return points


def entries_from_points(points: list[dict], *, green: bool,
                        source: str) -> list[dict]:
    """Ledger entries for a fresh run's points (appended after a green
    gate, so the observatory's history grows one honest sample per
    run)."""
    return [{
        "ts": time.time(), "key": p["key"], "metric": p["metric"],
        "workload": None, "unit": p.get("unit"), "plan": p.get("plan"),
        "green": bool(green), "source": source, "metrics": p["metrics"],
        "span_shares": p.get("span_shares"),
        "phases": p.get("phases"),
    } for p in points]


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------

def _attribution(point: dict, baseline_entry: dict) -> dict | None:
    """Which span's (else step-phase's) share of the run grew most vs
    the baseline — the pointer a flagged regression starts from."""
    for field, label in (("span_shares", "span"), ("phases", "phase")):
        fresh = (point.get(field) if field == "span_shares"
                 else phase_shares(point.get("phases")))
        base = (baseline_entry.get(field) if field == "span_shares"
                else phase_shares(baseline_entry.get("phases")))
        if not fresh or not base:
            continue
        deltas = {k: fresh.get(k, 0.0) - base.get(k, 0.0)
                  for k in set(fresh) | set(base)}
        name, delta = max(deltas.items(), key=lambda kv: kv[1])
        if delta > 0:
            return {label: name, "share": round(fresh.get(name, 0.0), 4),
                    "baseline_share": round(base.get(name, 0.0), 4),
                    "grew_by": round(delta, 4)}
    return None


def gate_points(points: list[dict], ledger: list[dict], *,
                k: float = DEFAULT_K, rel_floor: float = DEFAULT_REL_FLOOR,
                history: int = DEFAULT_HISTORY) -> dict:
    """Compare fresh measurement points against the ledger.

    Returns ``{ok, regressions: [...], verdicts: [...], no_baseline:
    [...], k, rel_floor}`` — the payload of the typed ``gate`` record.
    Each verdict: ``{key, metric, value, baseline, tolerance, n_history,
    ok}`` (``metric`` is ``<headline>:<tracked metric>``).
    """
    verdicts: list[dict] = []
    regressions: list[dict] = []
    no_baseline: list[str] = []
    for pt in points:
        hist = [e for e in ledger if e.get("green")
                and e.get("key") == pt["key"] and e.get("metrics")]
        if not hist:
            # Entries predating plan embedding (BENCH r01-r05) carry no
            # plan; ONLY those match by headline metric name — an entry
            # measured under a *different* plan payload must never gate
            # this one (a dp4 number is not a dp8 baseline).
            hist = [e for e in ledger if e.get("green")
                    and e.get("metric") == pt["metric"] and e.get("metrics")
                    and e.get("plan") is None]
        if not hist:
            no_baseline.append(pt["key"])
            continue
        point_reg = None
        for mname, higher_better in GATE_METRICS.items():
            fresh = pt["metrics"].get(mname)
            vals = [e["metrics"].get(mname) for e in hist[-history:]]
            vals = [float(v) for v in vals if isinstance(v, (int, float))]
            if not isinstance(fresh, (int, float)) or not vals:
                continue
            med = median(vals)
            mad = median([abs(v - med) for v in vals])
            tol = max(k * 1.4826 * mad, rel_floor * abs(med))
            worse = (fresh < med - tol) if higher_better \
                else (fresh > med + tol)
            v = {"key": pt["key"], "metric": f"{pt['metric']}:{mname}",
                 "value": round(float(fresh), 6), "baseline": round(med, 6),
                 "tolerance": round(tol, 6), "n_history": len(vals),
                 "ok": not worse}
            verdicts.append(v)
            if worse:
                regressions.append(v)
                point_reg = point_reg or v
        if point_reg is not None:
            point_reg["attribution"] = _attribution(pt, hist[-1])
    return {
        "ok": not regressions,
        "regressions": regressions,
        "verdicts": verdicts,
        "no_baseline": no_baseline,
        "k": k, "rel_floor": rel_floor,
    }


def emit_gate_record(sink, result: dict, *, ledger_path: str) -> None:
    """Write the verdict as one typed ``gate`` record. ``sink`` is a
    live TelemetryRun (bench) or a stream path (the CLI appending to a
    finished run's stream — a raw JSONL line with the same schema, no
    second ``run_start`` header)."""
    fields = dict(result, ledger=ledger_path)
    if hasattr(sink, "record"):
        sink.record("gate", **fields)
        return
    line = json.dumps({"ts": time.time(), "kind": "gate", **fields},
                      default=str)
    with open(sink, "a") as f:
        f.write(line + "\n")
