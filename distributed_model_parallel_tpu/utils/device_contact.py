"""Hardened first device contact, shared by bench.py and the CLI drivers.

The round-5 TPU-tunnel outage turned ``jax.devices()`` into a raw
``JaxRuntimeError`` traceback the bench driver could not parse (VERDICT
weak #1); bench.py grew a bounded-retry + parseable-failure-record pattern
in PR 1, and this module extracts it so ``scripts/train_data_parallel.py``,
``scripts/train_lm.py`` and ``scripts/train_model_parallel.py`` share the
exact same failure contract:

* transient transport drops are retried with exponential backoff
  (``DMP_CONTACT_RETRIES`` / ``DMP_CONTACT_RETRY_DELAY_S``; bench.py's
  historical ``DMP_BENCH_RETRIES`` / ``DMP_BENCH_RETRY_DELAY_S`` spellings
  keep working);
* a permanently unreachable backend becomes ONE parseable JSON record on
  stdout (``{"error": "tpu-unreachable", ...}``) plus a telemetry
  ``failure`` record — never a stack trace. bench.py exits 0 afterwards
  (its driver ingests the record); the training drivers exit
  :data:`EXIT_TPU_UNREACHABLE` so a cluster supervisor can retry the job.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Distinct, documented exit status for "backend unreachable after retries"
# (training drivers; bench.py keeps its historical rc=0 contract).
EXIT_TPU_UNREACHABLE = 17


def _log(msg: str, prefix: str = "device-contact") -> None:
    print(f"[{prefix}] {msg}", file=sys.stderr, flush=True)


def _env(name: str, default: str) -> str:
    # New spelling first, bench.py's historical one second.
    return os.environ.get(f"DMP_CONTACT_{name}",
                          os.environ.get(f"DMP_BENCH_{name}", default))


def contact_devices(max_attempts: int | None = None,
                    delay_s: float | None = None, *,
                    log_prefix: str = "device-contact"):
    """First device contact, hardened: bounded retry with exponential
    backoff, returning the device list or None after permanent failure
    (the last exception lands on ``contact_devices.last_error`` and the
    attempt count on ``contact_devices.attempts``).
    """
    import jax
    import jax.numpy as jnp

    if max_attempts is None:
        max_attempts = int(_env("RETRIES", "5"))
    if delay_s is None:
        delay_s = float(_env("RETRY_DELAY_S", "2.0"))
    max_attempts = max(1, max_attempts)
    contact_devices.attempts = max_attempts
    last: Exception | None = None
    for attempt in range(max_attempts):
        try:
            devs = jax.devices()
            # A device listing can succeed while the transport is dead;
            # prove liveness with one tiny round trip.
            jnp.zeros(()).block_until_ready()
            contact_devices.attempts = attempt + 1
            return devs
        except Exception as e:      # noqa: BLE001 - anything here is fatal
            last = e
            first_line = (str(e).splitlines() or [""])[0][:200]
            _log(f"device contact attempt {attempt + 1}/{max_attempts} "
                 f"failed: {type(e).__name__}: {first_line}", log_prefix)
            try:
                # jax caches a failed backend init; clear so the retry
                # actually re-dials instead of replaying the cached error.
                from jax.extend import backend as _backend

                _backend.clear_backends()
            except Exception:
                pass
            if attempt < max_attempts - 1:
                time.sleep(delay_s)
                delay_s *= 2
    contact_devices.last_error = last
    return None


def emit_unreachable(stage: str, err: Exception | None, attempts: int, *,
                     telemetry_path: str | None = None,
                     run_name: str | None = None) -> dict:
    """One parseable JSON failure record on stdout plus (best-effort) a
    telemetry ``failure`` record — the driver-facing form of a permanently
    unreachable backend. Returns the record.

    ``telemetry_path`` defaults to ``DMP_TELEMETRY`` (no stream written
    when unset); bench.py passes its historical default path so its
    failure stream keeps landing next to the bench logs.
    """
    detail = f"{type(err).__name__}: {err}" if err is not None else ""
    record = {
        "error": "tpu-unreachable",
        "stage": stage,
        "attempts": attempts,
        "detail": detail[:500],
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "ts": time.time(),
        "metric": None,
        "value": None,
    }
    # stdout record FIRST: the caller's supervisor must get the parseable
    # line promptly; the telemetry append is bookkeeping after the fact.
    print(json.dumps(record), flush=True)
    path = (telemetry_path if telemetry_path is not None
            else os.environ.get("DMP_TELEMETRY"))
    if path:
        try:
            from distributed_model_parallel_tpu.utils.telemetry import (
                TelemetryRun,
            )

            # device override: writing the header must not re-dial the
            # dead backend (device_info() would re-init it).
            t = TelemetryRun(path, run=run_name or f"{stage}-failure",
                             meta=dict(stage=stage),
                             device={"error": detail[:200] or "unreachable"})
            t.failure("tpu-unreachable", stage=stage, attempts=attempts,
                      detail=detail[:500])
            t.finish()
        except Exception:
            pass
    return record


def require_devices(stage: str, *, log_prefix: str | None = None):
    """The training-driver entry: contact the backend, or emit the failure
    record and exit ``EXIT_TPU_UNREACHABLE``. Returns the device list."""
    devs = contact_devices(log_prefix=log_prefix or stage)
    if devs is None:
        emit_unreachable(stage, getattr(contact_devices, "last_error", None),
                         getattr(contact_devices, "attempts", 0))
        raise SystemExit(EXIT_TPU_UNREACHABLE)
    return devs
