"""Unified run telemetry: metrics registry + structured JSONL event stream.

The reference's observability is ``time.time()`` deltas averaged per epoch
(``utils.py:41-74``). Before this module ours was fragmented the same way —
``StepTimer``/``AverageMeter`` meters, a ``RunLogger`` JSONL stream, and an
xplane trace parser that never fed one another. This module is the single
telemetry layer all of them now share:

* a process-wide :class:`MetricsRegistry` (counters, gauges, fixed-bucket
  histograms) — **host-side only, never inside jit**: metrics record Python
  floats at dispatch/drain/trace time, they are not traced values;
* a :class:`TelemetryRun` event stream — one JSONL file per run holding
  typed records (``run_start``, ``step``, ``epoch``, ``event``, ``memory``,
  ``metrics``, ``run_end``, ``failure``) that ``scripts/dmp_report.py``
  turns into step-time percentiles, throughput, MFU, comm volume and
  memory-watermark answers;
* collective communication-volume accounting
  (:func:`record_collective`), called by the ``ops/collectives.py``
  wrappers **at trace time** — each compilation of a program that uses a
  wrapper records its estimated per-device wire bytes once, tagged by mesh
  axis. Trace-time means the numbers are per *compile*, not per executed
  step: multiply by the step count for a program that retraces once (the
  steady state), and read them as "what one dispatch moves".

Record schema (all records carry ``ts`` (unix seconds) and ``kind``; runs
opened inside a :func:`tenant_scope` — the multi-tenant orchestrator wraps
each tenant's trainer in one — additionally stamp ``tenant`` on every
record, and :func:`merge_streams` joins per-tenant streams into the
ts-ordered fleet view the report renders):

========== ==========================================================
kind       payload keys
========== ==========================================================
run_start  run, jax, device {platform, device_kind, n_devices,
           process_index}, meta {workload-specific, e.g.
           model_flops_per_step, batch_size, mesh}
step       epoch, step, step_time_s, data_time_s, loss,
           samples_per_s | tokens_per_s, workload extras
epoch      epoch, loss_train, loss_val, time_per_batch, ...
event      message (free-form: preemption, guard trips)
memory     devices: [{id, platform, bytes_in_use, peak_bytes_in_use}]
metrics    counters, gauges, histograms (registry snapshot)
run_end    wall_s, plus caller extras
failure    error, detail, attempts, stage — a detected failure (guards,
           torn checkpoint, stall, preemption, unreachable backend)
recovery   action, plus context (slot, epoch, retries_left, lr_scale) —
           a recovery action taken by train/resilience.RecoverySupervisor;
           every failure record the supervisor handles gets a matching
           recovery record, and scripts/dmp_report.py renders the pair
           timeline
consistency status (divergence | repaired | no-quorum | non-finite),
           plus context (replicas, outliers, leaves, check index) — one
           cross-replica consistency-sentinel event
           (train/consistency.py); a
           divergence gets a matching ``recovery`` record
           (replica-rebroadcast or restored) on the same timeline
resume     slot, plus the exact continuation position (epoch,
           batch_cursor, global_step) and mesh context (saved_mesh vs
           mesh when the topology changed) — one elastic-resume event
           (train/elastic.py) emitted when a restarted run restores a
           checkpoint
fault      fault (kind), site, index — one injected fault firing
           (train/resilience.py on_fire); the anchor the fleet
           report's ledger pairs detections/recoveries against
tenant     name, event (submitted/admitted/preempt-requested/preempted/
           completed/failed/cancelled/grow-back), devices, global_step,
           priority — one tenant lifecycle transition on the
           orchestrator's fleet stream (orchestrator/orchestrator.py)
health     event (degrading | quarantine | reinstate), devices, score,
           signal, value, baseline, round — one device-health-sentinel
           transition (utils/health.py) on the fleet stream; a
           quarantine is followed by its holders' ``tenant``
           preempt-requested records with reason=device-degraded (the
           proactive migration), a reinstate by possible ``grow-back``
           records
serve      event (completed | failed | summary) plus the per-request
           SLO payload (prompt_tokens, new_tokens, queue_wait_s,
           ttft_s, token_latency_s) or the engine-run aggregate
           (policy, tokens_per_s, slot_utilization, page_occupancy) —
           the serving engine's records (serve/engine.py; a failed
           event carries the typed ``engine-killed`` error, never a
           silent drop)
span       name, t0 (wall-clock start), dur_s (monotonic duration),
           sid, parent, depth, thread, plus site attrs — one timed
           interval from the span API (utils/tracing.py): trainer
           epochs/drains/evals, checkpoint I/O, engine prefill chunks
           and decode rounds, orchestrator rounds; ``ts`` is the
           wall-clock end. scripts/dmp_trace.py renders these as a
           zoomable Chrome/Perfetto timeline
gate       ok, regressions [{metric, value, baseline, tolerance}],
           attribution {span|phase, share, baseline_share} — one
           cross-run perf-regression-gate verdict (utils/baseline.py,
           scripts/dmp_gate.py) comparing this run's headline metrics
           against the baseline ledger's noise band
alert      rule, subject, state (firing | resolved), value, threshold,
           plus per-rule detail — one DEDUPLICATED SLO-alert transition
           (utils/alerts.py): step-time drift vs the baseline band,
           serve burn rate, page saturation, health floor; written by
           the orchestrator's control loop, fsync'd on write
postmortem reason, bundle (directory path), n_records, error — the
           crash flight recorder (utils/flightrec.py) wrote a
           postmortem bundle (ring-buffer record tail, all-thread
           stacks, span stacks, device memory, health scores);
           fsync'd so the pointer survives the crash it describes
========== ==========================================================

Two live surfaces sit on top of this stream: the statusz exporter
(utils/statusz.py — /metrics Prometheus text with per-tenant labels,
/statusz JSON, /healthz) and the live-tail reader
(:class:`StreamFollower` / :func:`follow_records` — rotation-safe
incremental reads; the cockpit scripts/dmp_top.py and the alert
engine's ingest path).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Mapping

__all__ = [
    "AlreadyRegisteredError",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RTRACE_TERMINAL_EVENTS",
    "StreamFollower",
    "TelemetryRun",
    "current_tenant",
    "device_info",
    "device_memory_snapshot",
    "follow_records",
    "install_compile_tracking",
    "join_request_traces",
    "live_runs",
    "merge_streams",
    "read_records",
    "record_collective",
    "record_tap",
    "registry",
    "set_record_tap",
    "stream_parts",
    "tenant_scope",
    "wire_bytes_estimate",
    "wire_ops_estimate",
]


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic float counter.

    Increments made on a thread bound to a :func:`tenant_scope` are
    *additionally* attributed to that tenant's bucket — the orchestrator
    runs each tenant's trainer on its own scoped thread, so a
    co-resident tenant's compile/comm-volume counters are separable from
    fleet totals (``MetricsRegistry.snapshot(tenant=...)``)."""

    __slots__ = ("value", "by_tenant")

    def __init__(self):
        self.value = 0.0
        self.by_tenant: dict[str, float] = {}

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increments must be >= 0, got {n}")
        self.value += float(n)
        tenant = current_tenant()
        if tenant is not None:
            self.by_tenant[tenant] = self.by_tenant.get(tenant, 0.0) + float(n)


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v: float) -> None:
        self.value = float(v)


# Default histogram buckets: log-spaced, 5 per decade, 10us..100s — wide
# enough for per-step latencies on CPU tests and tunnel-latency TPU runs
# alike. Quantiles interpolate within a bucket, so the estimate error is
# bounded by the bucket ratio (10^0.2 ~ 1.58x worst case).
DEFAULT_TIME_BUCKETS: tuple[float, ...] = tuple(
    10 ** (-5 + i / 5) for i in range(36))


class Histogram:
    """Fixed-bucket histogram with interpolated quantiles.

    Exact ``count``/``sum``/``min``/``max``; quantiles come from the bucket
    cumulative counts with linear interpolation inside the crossing bucket.
    ``observe(v, exemplar=...)`` keeps the last exemplar label (a request
    trace id) per bucket, so the /metrics exposition can attach an
    OpenMetrics-style exemplar to each ``_bucket`` series — the hook that
    lets "p99 TTFT regressed" link straight to a traceable request.
    """

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max",
                 "exemplars")

    def __init__(self, bounds: Iterable[float] | None = None):
        self.bounds = tuple(sorted(bounds or DEFAULT_TIME_BUCKETS))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.counts = [0] * (len(self.bounds) + 1)  # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        # bucket index -> (exemplar label, observed value); last wins.
        self.exemplars: dict[int, tuple[str, float]] = {}

    def observe(self, v: float, exemplar: str | None = None) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        # First bound >= v (linear scan: bucket counts are small and this
        # is host-side bookkeeping, not a hot loop).
        idx = len(self.counts) - 1
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        self.counts[idx] += 1
        if exemplar is not None:
            self.exemplars[idx] = (str(exemplar), v)

    def percentile(self, q: float) -> float | None:
        """Interpolated q-th percentile (q in [0, 100]); None when empty."""
        if self.count == 0:
            return None
        target = q / 100.0 * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if cum + c >= target and c > 0:
                # Bucket i spans (lo, hi]; clamp to observed min/max so a
                # single-sample histogram reports the sample, not a bound.
                lo = self.bounds[i - 1] if i > 0 else self.min
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                lo, hi = max(lo, self.min), min(hi, self.max)
                frac = (target - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        return self.max

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class AlreadyRegisteredError(ValueError):
    """A metric name+tags was reused with a different metric type."""


def _fmt_key(name: str, tags: tuple[tuple[str, str], ...]) -> str:
    if not tags:
        return name
    inner = ",".join(f"{k}={v}" for k, v in tags)
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Process-wide named metrics, keyed by (name, sorted tags)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, Any] = {}

    def _get(self, cls, name: str, tags: Mapping[str, Any], **kw):
        key = (name, tuple(sorted((k, str(v)) for k, v in tags.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(**kw)
            elif not isinstance(m, cls):
                raise AlreadyRegisteredError(
                    f"{_fmt_key(*key)} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, **tags) -> Counter:
        return self._get(Counter, name, tags)

    def gauge(self, name: str, **tags) -> Gauge:
        return self._get(Gauge, name, tags)

    def histogram(self, name: str, bounds: Iterable[float] | None = None,
                  **tags) -> Histogram:
        return self._get(Histogram, name, tags, bounds=bounds)

    def items(self) -> list[tuple[str, dict[str, str], Any]]:
        """A consistent view of every registered metric:
        ``(name, {tag: value}, metric_object)`` rows, name-sorted. The
        statusz exporter's ``/metrics`` renderer walks this (it needs the
        live objects — e.g. a Counter's per-tenant buckets — not the
        JSON snapshot)."""
        with self._lock:
            rows = list(self._metrics.items())
        return [(name, dict(tags), m)
                for (name, tags), m in sorted(rows, key=lambda kv: kv[0])]

    def snapshot(self, tenant: str | None = None) -> dict:
        """JSON-ready dump: {"counters": {...}, "gauges": {...},
        "histograms": {...}} with ``name{k=v,...}`` keys.

        With ``tenant``, counters report only the increments made inside
        that tenant's :func:`tenant_scope` (per-tenant attribution);
        gauges and histograms have no per-tenant buckets and stay
        process-global."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = list(self._metrics.items())
        for (name, tags), m in sorted(items, key=lambda kv: kv[0]):
            key = _fmt_key(name, tags)
            if isinstance(m, Counter):
                out["counters"][key] = (m.value if tenant is None
                                        else m.by_tenant.get(tenant, 0.0))
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.snapshot()
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_default_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (collectives accounting, compile counts)."""
    return _default_registry


# ---------------------------------------------------------------------------
# Recompilation tracking (jax.monitoring)
# ---------------------------------------------------------------------------

_compile_tracking_installed = False


def install_compile_tracking() -> bool:
    """Count backend compilations into ``registry().counter("jax_compiles")``.

    Uses the public ``jax.monitoring`` listener API
    (``/jax/core/compile/backend_compile_duration`` fires once per XLA
    compile — i.e. once per trace-cache miss, which is exactly what a
    "recompilation count" should mean). Idempotent; returns whether the
    listener is installed. Total compile seconds accumulate alongside in
    ``jax_compile_seconds`` so the report can say how much wall time
    compilation ate.
    """
    global _compile_tracking_installed
    if _compile_tracking_installed:
        return True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event.endswith("backend_compile_duration"):
                reg = registry()
                reg.counter("jax_compiles").inc()
                reg.counter("jax_compile_seconds").inc(max(0.0, duration))

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:        # pragma: no cover - jax without monitoring
        return False
    _compile_tracking_installed = True
    return True


# ---------------------------------------------------------------------------
# Collective communication-volume accounting (called at trace time)
# ---------------------------------------------------------------------------

# Per-device wire bytes moved by one execution of a collective over an
# n-way axis, as a fraction of the logical payload — the standard ring
# algorithm costs. ppermute sends the whole shard once; all-reduce is
# reduce-scatter + all-gather.
_WIRE_FACTORS = {
    "psum": lambda n: 2 * (n - 1) / n,
    "bucketed_psum": lambda n: 2 * (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}


def wire_bytes_estimate(kind: str, payload_bytes: int, n_shards: int) -> float:
    """Estimated per-device wire bytes for one execution of a collective.

    ``payload_bytes`` is the LOGICAL payload: the full reduced tree for
    psum/reduce_scatter, the full gathered result for all_gather, the
    per-device shard for ppermute. Ring-algorithm cost model; actual ICI
    traffic depends on the topology XLA picks, so treat as an estimate.
    """
    n = max(1, int(n_shards))
    factor = _WIRE_FACTORS.get(kind)
    if factor is None:
        factor = lambda n: 1.0  # noqa: E731 - unknown kinds count payload
    return float(payload_bytes) * factor(n)


# Per-device sequential message count of one execution under the same
# ring algorithms — the ALPHA term of an alpha-beta cost model (each
# message pays a launch/latency cost regardless of size, which is what
# makes many small collectives slower than one big one even at equal
# bytes). All-reduce = reduce-scatter (n-1 steps) + all-gather (n-1).
_OP_FACTORS = {
    "psum": lambda n: 2 * (n - 1),
    "bucketed_psum": lambda n: 2 * (n - 1),
    "reduce_scatter": lambda n: n - 1,
    "all_gather": lambda n: n - 1,
    "all_to_all": lambda n: n - 1,
    "ppermute": lambda n: 1,
}


def wire_ops_estimate(kind: str, n_shards: int) -> float:
    """Per-device message count for one execution of a collective over an
    n-way axis (ring model; unknown kinds count one message). The
    companion of :func:`wire_bytes_estimate`: together they are the
    (alpha, beta) pair the autotuner's cost model prices collectives
    with (autotune/cost_model.py)."""
    n = max(1, int(n_shards))
    factor = _OP_FACTORS.get(kind)
    if factor is None:
        factor = lambda n: 1.0  # noqa: E731 - unknown kinds count one op
    return float(factor(n))


def record_collective(kind: str, axis: Any, payload_bytes: Any,
                      n_shards: Any) -> None:
    """Account one collective call into the registry, tagged by mesh axis.

    Called by the ``ops/collectives.py`` wrappers while they trace. Never
    raises: a tracer leaking into ``n_shards`` (dynamic axis size) or any
    other surprise silently skips the sample rather than breaking the
    user's jit. Counters written (see module docstring for trace-time
    semantics):

    * ``collective_traces{kind,axis}`` — times this collective traced;
    * ``collective_payload_bytes{kind,axis}`` — logical payload bytes;
    * ``collective_wire_bytes_est{kind,axis}`` — ring-model wire bytes;
    * ``collective_ops_est{kind,axis}`` — ring-model per-device message
      count (the alpha term of an alpha-beta cost model needs message
      counts, not just bytes — autotune/cost_model.py seeds from both).
    """
    try:
        n = int(n_shards)
        b = int(payload_bytes)
        axis_s = axis if isinstance(axis, str) else ",".join(map(str, axis))
        reg = registry()
        tags = dict(kind=kind, axis=axis_s)
        reg.counter("collective_traces", **tags).inc()
        reg.counter("collective_payload_bytes", **tags).inc(b)
        reg.counter("collective_wire_bytes_est", **tags).inc(
            wire_bytes_estimate(kind, b, n))
        reg.counter("collective_ops_est", **tags).inc(
            wire_ops_estimate(kind, n))
    except Exception:
        return


# ---------------------------------------------------------------------------
# Device probes (host-side, guarded: must never take a run down)
# ---------------------------------------------------------------------------

def device_info() -> dict:
    """Backend identity for the run_start record; {"error": ...} when the
    backend is unreachable (bench failure records still need a header)."""
    try:
        import jax

        devs = jax.devices()
        d0 = devs[0]
        return {
            "platform": d0.platform,
            "device_kind": getattr(d0, "device_kind", "") or "",
            "n_devices": len(devs),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def device_memory_snapshot() -> list[dict] | None:
    """Per-device memory watermarks via ``memory_stats()`` where the backend
    implements it (TPU/GPU); None when no device reports (CPU returns
    None per device)."""
    try:
        import jax

        out = []
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            rec = {"id": d.id, "platform": d.platform}
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                      "largest_alloc_size"):
                if k in stats:
                    rec[k] = int(stats[k])
            out.append(rec)
        return out or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Tenant tagging (multi-tenant orchestration, orchestrator/)
# ---------------------------------------------------------------------------

# Thread-local "who is writing telemetry right now": the orchestrator runs
# each tenant's trainer on its own thread and wraps construction + fit in
# ``tenant_scope(name)``, so every TelemetryRun a trainer opens inside that
# scope tags its records without the trainers knowing tenancy exists.
_tenant_local = threading.local()


def current_tenant() -> str | None:
    """The tenant name bound to this thread (None outside any scope)."""
    return getattr(_tenant_local, "name", None)


@contextlib.contextmanager
def tenant_scope(name: str):
    """Bind a tenant name to the current thread: every
    :class:`TelemetryRun` constructed inside the scope stamps ``tenant``
    onto all of its records (the fleet report groups by it). Scopes nest;
    the previous binding is restored on exit."""
    prev = current_tenant()
    _tenant_local.name = str(name)
    try:
        yield
    finally:
        _tenant_local.name = prev


def merge_streams(paths: Iterable[str]) -> list[dict]:
    """Merge several telemetry JSONL streams into one ts-ordered record
    list — the fleet view ``scripts/dmp_report.py`` renders for a
    multi-tenant run. Records keep their per-stream ``tenant`` tags;
    untagged records from a stream whose ``run_start`` carries one inherit
    it (legacy streams predating the tag merge untagged). Missing files
    are skipped (a tenant killed before its header wrote nothing)."""
    merged: list[tuple[float, int, dict]] = []
    order = 0
    paths = list(paths)
    # A shell glob over a rotated stream lists run.jsonl AND its
    # run.N.jsonl parts; read_records(run.jsonl) already folds the parts
    # in, so a listed path that is some other listed path's rotation
    # part must be skipped or its records would merge twice.
    absorbed = {os.path.abspath(part)
                for p in paths for part in stream_parts(p)
                if os.path.abspath(part) != os.path.abspath(p)}
    for path in paths:
        if os.path.abspath(path) in absorbed:
            continue
        try:
            records = read_records(path)
        except FileNotFoundError:
            continue
        tenant = next((r.get("tenant") for r in records
                       if r.get("kind") == "run_start"), None)
        for r in records:
            if tenant is not None and "tenant" not in r:
                r = {**r, "tenant": tenant}
            ts = r.get("ts")
            merged.append((ts if isinstance(ts, (int, float)) else 0.0,
                           order, r))
            order += 1
    merged.sort(key=lambda t: (t[0], t[1]))
    return [r for _, _, r in merged]


# ---------------------------------------------------------------------------
# Request-trace joining (the serving tier's per-request X-ray)
# ---------------------------------------------------------------------------

# Events that END a request's timeline — every admitted request must
# terminate in exactly one of these, or the trace is an orphan (the
# dmp_soak drill gates and scripts/dmp_xray.py --gate enforce it).
RTRACE_TERMINAL_EVENTS = frozenset({"completed", "shed", "expired",
                                    "failed"})

# Events a request emits while it is still waiting (before any prefill
# work) — the interval LEADING INTO one of these is queue time.
_RTRACE_QUEUE_EVENTS = frozenset({"submitted", "route", "admitted",
                                  "clamp", "memory_stall", "shed",
                                  "expired", "failed"})


def _rtrace_origin(rec: dict) -> str:
    """Which emitter a record came from — the ``replica`` field in fleet
    mode (the fleet and its replica engines share one stream), falling
    back to the physical-stream tag dmp_xray stamps when joining several
    files. Migration hops link where this changes across an
    export/import pair."""
    v = rec.get("replica")
    if v is None:
        v = rec.get("stream")
    return str(v) if v is not None else ""


def _rtrace_phase(prev: dict, nxt: dict, clamped: bool,
                  prefilled: bool) -> str:
    """Attribute the interval between two consecutive (by seq) rtrace
    events to one phase. The rules partition a trace's whole ts span, so
    per-phase seconds sum exactly to the timeline's wall time."""
    pe, ne = prev.get("event"), nxt.get("event")
    if pe == "export" or ne in ("import", "recovered"):
        # The interval INTO a ``recovered`` event is crash downtime —
        # the request sat in a dead replica's abandoned state (or a
        # downed fleet's journal) until recovery re-admitted it; same
        # bucket as a graceful migration's pause.
        return "migration-pause"
    if pe == "memory_stall":
        return "memory-stall"
    if ne == "prefill":
        return "prefill"
    if ne in _RTRACE_QUEUE_EVENTS and not prefilled:
        return "queue"
    if ne in ("decode", "completed") or (ne in RTRACE_TERMINAL_EVENTS
                                         and prefilled):
        return "brownout-clamp" if clamped else "decode"
    return "other"


def join_request_traces(records: Iterable[dict]) -> dict[str, dict]:
    """Fold ``rtrace`` records (one or more merged streams) into causally
    ordered per-request timelines, keyed by trace id.

    Ordering is by the per-request ``seq`` stamped at emission — NOT by
    ``ts`` — so two events inside one engine iteration (identical wall
    stamps) and events split across replica streams by a migration still
    reconstruct in their true causal order. Each timeline carries:

    * ``events`` — the records, causally ordered by (epoch, seq): a
      full fleet restart resets a request's seq counter to 1, so a seq
      DROP in record order starts a new epoch — the restart's
      ``recovered`` event must open it, or the trace is an orphan;
    * ``terminal`` — the single terminal event name (completed / shed /
      expired / failed), or None;
    * ``hops`` — migration hops, linked wherever an ``export`` is
      followed (by seq; the migration re-route record may intervene)
      by an ``import`` whose emitting replica/stream differs, PLUS one
      export-less hop per ``recovered`` event (a crash moves the
      request with no export — the journal is the carrier):
      ``{seq, from, to}`` (``recovered: True`` on crash hops);
    * ``orphan`` / ``orphan_reasons`` — a seq gap (a lost span, or a
      restart that skipped the ``recovered`` wiring — its duplicate
      seqs collapse into one), zero terminals (a silently dropped
      request) or more than one (a double-accounted one);
    * ``phases`` — seconds per phase (queue / prefill / decode /
      brownout-clamp / migration-pause / memory-stall / other) from an
      interval partition of the event timestamps: phases sum exactly to
      ``wall_s`` (= last ts - first ts) by construction. Crash downtime
      (the interval into a ``recovered`` event) lands in
      ``migration-pause``.
    """
    by_trace: dict[str, list[dict]] = {}
    for r in records:
        if r.get("kind") != "rtrace" or r.get("trace") is None:
            continue
        by_trace.setdefault(str(r["trace"]), []).append(r)
    out: dict[str, dict] = {}
    for trace, raw in by_trace.items():
        # Epoch split FIRST, in record order: a request's seq counter
        # restarts at 1 when a fleet restart rebuilds the Request object
        # from the journal, and the restart's ``recovered`` event is the
        # first record the new process emits for it — so a non-
        # increasing seq ON a ``recovered`` event marks the process
        # boundary. A seq drop WITHOUT one (interleaved multi-stream
        # input) stays in the same epoch, where the per-epoch sort
        # recovers causal order — and a restart that skipped the
        # ``recovered`` wiring collapses into duplicate seqs, flagged as
        # a seq-gap orphan below (an unlinked restart is an orphan, not
        # a hop).
        epochs: list[list[dict]] = [[]]
        last_seq = None
        for r in raw:
            s = int(r.get("seq") or 0)
            if (last_seq is not None and s <= last_seq
                    and r.get("event") == "recovered"):
                epochs.append([])
            epochs[-1].append(r)
            last_seq = s
        for ep in epochs:
            ep.sort(key=lambda r: (r.get("seq") or 0))
        evs = [r for ep in epochs for r in ep]
        reasons: list[str] = []
        for ep in epochs:
            seqs = [int(r.get("seq") or 0) for r in ep]
            if seqs != list(range(1, len(ep) + 1)):
                reasons.append("seq-gap")
                break
        terminals = [r for r in evs
                     if r.get("event") in RTRACE_TERMINAL_EVENTS]
        if not terminals:
            reasons.append("no-terminal")
        elif len(terminals) > 1:
            reasons.append("multiple-terminals")
        # Pair each export with the NEXT import (the migration re-route
        # emits a ``route`` record between them, so strict adjacency
        # would miss the hop). A ``recovered`` event is an export-LESS
        # hop: the source died without draining, the journal carried
        # the request — ``from`` is the dead replica, ``to`` the next
        # event's origin (the post-recovery route decision).
        hops = []
        pending_export = None
        for j, r in enumerate(evs):
            if r.get("event") == "export":
                pending_export = r
            elif r.get("event") == "import" and pending_export is not None:
                if _rtrace_origin(pending_export) != _rtrace_origin(r):
                    hops.append({"seq": pending_export.get("seq"),
                                 "from": _rtrace_origin(pending_export),
                                 "to": _rtrace_origin(r)})
                pending_export = None
            elif r.get("event") == "recovered":
                src = r.get("from_replica")
                if src is None and j > 0:
                    src = _rtrace_origin(evs[j - 1])
                dst = (_rtrace_origin(evs[j + 1]) if j + 1 < len(evs)
                       else _rtrace_origin(r))
                hops.append({"seq": r.get("seq"),
                             "from": str(src) if src is not None else "",
                             "to": dst, "recovered": True})
        phases: dict[str, float] = {}
        clamped = prefilled = False
        for a, b in zip(evs, evs[1:]):
            phase = _rtrace_phase(a, b, clamped, prefilled)
            ta, tb = a.get("ts"), b.get("ts")
            dt = (max(0.0, tb - ta)
                  if isinstance(ta, (int, float))
                  and isinstance(tb, (int, float)) else 0.0)
            phases[phase] = phases.get(phase, 0.0) + dt
            if a.get("event") == "clamp":
                clamped = True
            if a.get("event") == "prefill":
                prefilled = True
        ts = [r["ts"] for r in evs
              if isinstance(r.get("ts"), (int, float))]
        out[trace] = {
            "trace": trace,
            "request": evs[0].get("request"),
            "events": evs,
            "terminal": (terminals[0].get("event") if len(terminals) == 1
                         else None),
            "hops": hops,
            "orphan": bool(reasons),
            "orphan_reasons": reasons,
            "phases": phases,
            "t0": min(ts) if ts else None,
            "t1": max(ts) if ts else None,
            "wall_s": (max(ts) - min(ts)) if ts else 0.0,
        }
    return out


# ---------------------------------------------------------------------------
# The run event stream
# ---------------------------------------------------------------------------

# Process-wide record tap: when set, every record ANY TelemetryRun writes
# is also handed (as its final dict) to this callable — the crash flight
# recorder's free tee (utils/flightrec.py installs its ring buffer here).
# One None-check per record when unset; tap errors never break the write.
_record_tap: Callable[[dict], None] | None = None


def set_record_tap(fn: Callable[[dict], None] | None) -> None:
    """Install (or clear, with None) the process-wide record tap."""
    global _record_tap
    _record_tap = fn


def record_tap() -> Callable[[dict], None] | None:
    return _record_tap


# Live (not-yet-finished) runs, weakly held: the drivers' unhandled-
# exception hook (utils/flightrec.install_excepthook) closes these so a
# crash still gets its final metrics/run_end records.
_live_runs: "weakref.WeakSet[TelemetryRun]" = weakref.WeakSet()


def live_runs() -> list["TelemetryRun"]:
    """Every TelemetryRun constructed in this process that has not yet
    ``finish()``-ed (weakly tracked; GC'd runs drop out)."""
    return [r for r in list(_live_runs) if not r._finished]


# Record kinds that must survive the very crash they describe: the write
# is fsync'd before the stream lock releases, so a process dying right
# after (the common failure->abort path) cannot leave them torn in the
# page cache.
_DURABLE_KINDS = frozenset({"failure", "postmortem", "alert"})


def _coerce(v: Any) -> Any:
    """JSON-safe coercion: device/numpy scalars to float, containers
    element-wise; anything else through str() as a last resort."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, Mapping):
        return {str(k): _coerce(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_coerce(x) for x in v]
    if hasattr(v, "__float__"):
        try:
            return float(v)
        except Exception:
            pass
    return str(v)


class TelemetryRun:
    """Append-only JSONL event stream for one run.

    Opens (and creates directories for) ``path``, writes a ``run_start``
    header, then takes typed records. Thread-safe appends; every record is
    one line, flushed, so a killed run still leaves a parseable stream.
    """

    def __init__(self, path: str, *, run: str = "run",
                 meta: Mapping[str, Any] | None = None,
                 registry_: MetricsRegistry | None = None,
                 track_compiles: bool = True,
                 device: Mapping[str, Any] | None = None,
                 tenant: str | None = None,
                 max_bytes: int | None = None):
        self.path = path
        # Stream rotation for long runs: once the live file would exceed
        # ``max_bytes`` it is renamed to the next ``{stem}.N.jsonl`` part
        # and appends continue on a fresh file, so a long-mode soak
        # campaign cannot grow one unbounded stream. read_records /
        # merge_streams / the report glob the parts back in order
        # (stream_parts). Default: env DMP_TELEMETRY_MAX_BYTES, else off.
        if max_bytes is None:
            env = os.environ.get("DMP_TELEMETRY_MAX_BYTES")
            max_bytes = int(env) if env else None
        if max_bytes is not None and max_bytes < 4096:
            raise ValueError(
                f"max_bytes={max_bytes} would rotate on nearly every "
                f"record (one run_start header is hundreds of bytes); "
                f"use >= 4096 or None")
        self.max_bytes = max_bytes
        try:
            self._bytes = os.path.getsize(path)   # resumed stream appends
        except OSError:
            self._bytes = 0
        # Tenant tag: explicit, or inherited from the thread's
        # tenant_scope (how the orchestrator tags trainer-opened streams
        # without the trainers knowing). Stamped on every record.
        self.tenant = tenant if tenant is not None else current_tenant()
        self.registry = registry_ if registry_ is not None else registry()
        self._lock = threading.Lock()
        self._finished = False
        # Monotonic pair for the run_end wall_s duration: an NTP step
        # mid-run must not skew it (record ``ts`` stamps stay wall-clock
        # for cross-stream correlation).
        self._t0 = time.monotonic()
        # Counter baseline at stream open: the registry is process-global,
        # so a second run in the same process must not inherit the first
        # run's collective-volume / compile counts in its metrics record.
        # Tenant-tagged streams baseline (and later report) the TENANT's
        # own counter bucket, so a co-resident tenant's metrics record
        # carries per-tenant deltas, not fleet totals.
        self._counter_baseline = dict(
            self.registry.snapshot(tenant=self.tenant)
            .get("counters", {}))
        # Step-time histogram is RUN-LOCAL (histograms have no delta
        # semantics, so sharing the global registry would merge runs).
        self._step_hist = Histogram()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        if track_compiles:
            install_compile_tracking()
        try:
            import jax

            jax_version = jax.__version__
        except Exception:        # pragma: no cover - jax always present here
            jax_version = None
        # ``device`` override: callers reporting a DEAD backend (bench
        # failure records) must not re-dial it just to write the header —
        # device_info() would re-initialize the backend from scratch.
        self.record("run_start", run=run, jax=jax_version,
                    device=dict(device) if device is not None
                    else device_info(),
                    meta=_coerce(dict(meta or {})))
        _live_runs.add(self)

    def record(self, kind: str, **fields) -> None:
        head = {"ts": time.time(), "kind": kind}
        if self.tenant is not None:
            head["tenant"] = self.tenant
        rec = {**head, **{k: _coerce(v) for k, v in fields.items()}}
        tap = _record_tap
        if tap is not None:
            # The crash flight recorder's tee (utils/flightrec.py): the
            # ring gets the record BEFORE the disk write, so even a
            # write that dies mid-line reaches the postmortem bundle.
            try:
                tap(rec)
            except Exception:
                pass
        line = json.dumps(rec, default=str)
        with self._lock:
            n = len(line.encode("utf-8")) + 1    # bytes written, not chars
            if (self.max_bytes is not None and self._bytes > 0
                    and self._bytes + n > self.max_bytes):
                self._rotate()
            with open(self.path, "a") as f:
                f.write(line + "\n")
                if kind in _DURABLE_KINDS:
                    # Crash hygiene: a failure/postmortem/alert record is
                    # exactly the record a crashing process must not lose
                    # — flush + fsync before the lock releases, so the
                    # line is on disk even if the process dies next.
                    f.flush()
                    try:
                        os.fsync(f.fileno())
                    except OSError:
                        pass
            self._bytes += n

    def _rotate(self) -> None:
        """Rename the live file to the next ``{stem}.N.jsonl`` part
        (called under the record lock)."""
        stem, ext = os.path.splitext(self.path)
        existing = _part_indices(self.path)
        nxt = (max(existing) + 1) if existing else 1
        try:
            os.replace(self.path, f"{stem}.{nxt}{ext}")
        except OSError:
            return          # rotation is best-effort; keep appending
        self._bytes = 0

    def step(self, **fields) -> None:
        """One training/bench step (or drain window) worth of timings.
        Conventional keys: epoch, step, step_time_s, data_time_s, loss,
        samples_per_s or tokens_per_s. Step times also feed a run-local
        ``step_time_s`` histogram, so the final metrics record carries
        bucket-quantile estimates next to the raw records."""
        t = fields.get("step_time_s")
        if isinstance(t, (int, float)) and not isinstance(t, bool):
            self._step_hist.observe(t)
        self.record("step", **fields)

    def epoch(self, **fields) -> None:
        self.record("epoch", **fields)

    def event(self, message: str) -> None:
        self.record("event", message=message)

    def failure(self, error: str, **fields) -> None:
        self.record("failure", error=error, **fields)

    def recovery(self, action: str, **fields) -> None:
        """One recovery action (restore, fallback, checkpoint-and-exit,
        save retry) — the matching half of a ``failure`` record."""
        self.record("recovery", action=action, **fields)

    def consistency(self, status: str, **fields) -> None:
        """One cross-replica consistency-sentinel event
        (train/consistency.py): ``divergence`` when replicas disagree,
        ``repaired`` after an in-place re-broadcast, ``no-quorum`` when no
        majority-good replica exists and the supervisor's good-slot
        restore takes over, ``non-finite`` when replicas agree on a
        non-finite state (routed to the NonFiniteError recovery path)."""
        self.record("consistency", status=status, **fields)

    def resume(self, slot: str, **fields) -> None:
        """One elastic-resume event (train/elastic.py): which checkpoint
        slot a restarted run picked up, the exact position it continues
        from (epoch, batch cursor, global step) and the saving vs current
        mesh when the topology changed — so a restart is auditable on the
        resilience timeline, not inferred from step numbering."""
        self.record("resume", slot=slot, **fields)

    def memory(self) -> list[dict] | None:
        """Record device memory watermarks (no-op record skipped when the
        backend reports none, e.g. CPU)."""
        snap = device_memory_snapshot()
        if snap:
            self.record("memory", devices=snap)
        return snap

    def metrics(self) -> None:
        """Snapshot the registry into the stream.

        Counters are reported as DELTAS since this stream opened (the
        registry is process-global; without the baseline a second run in
        the same process would re-report the first run's comm volume and
        compile counts). A tenant-tagged stream reports the tenant's own
        counter bucket — increments made inside its ``tenant_scope`` —
        so co-resident tenants' deltas are per-tenant, not fleet totals.
        The ``step_time_s`` histogram is run-local, so its quantiles
        describe only this run; gauges and any caller-made registry
        histograms are absolute."""
        snap = self.registry.snapshot(tenant=self.tenant)
        base = self._counter_baseline
        snap["counters"] = {k: v - base.get(k, 0)
                            for k, v in snap.get("counters", {}).items()}
        if self._step_hist.count:
            snap.setdefault("histograms", {})["step_time_s"] = \
                self._step_hist.snapshot()
        self.record("metrics", **snap)

    def finish(self, **fields) -> None:
        """Write the final ``metrics`` + ``run_end`` records (idempotent)."""
        if self._finished:
            return
        self._finished = True
        self.metrics()
        self.record("run_end", wall_s=time.monotonic() - self._t0, **fields)


def _part_indices(path: str) -> list[int]:
    """Existing rotation-part indices for a logical stream path."""
    import re

    stem, ext = os.path.splitext(os.path.basename(path))
    parent = os.path.dirname(os.path.abspath(path))
    pat = re.compile(re.escape(stem) + r"\.(\d+)" + re.escape(ext) + r"$")
    try:
        entries = os.listdir(parent)
    except OSError:
        return []
    return sorted(int(m.group(1)) for e in entries
                  for m in [pat.match(e)] if m)


def stream_parts(path: str) -> list[str]:
    """Every on-disk file of a logical stream, oldest first: the rotated
    ``{stem}.N.jsonl`` parts in numeric order, then the live file. A
    never-rotated stream is just ``[path]``."""
    stem, ext = os.path.splitext(path)
    out = [f"{stem}.{i}{ext}" for i in _part_indices(path)]
    if os.path.exists(path):
        out.append(path)
    return out


def read_records(path: str) -> list[dict]:
    """Parse a telemetry JSONL stream — all rotated parts in order, then
    the live file — skipping any truncated/corrupt line (a run killed
    mid-write leaves a partial final record; it must cost a warning, not
    poison a whole fleet merge). Every skipped line increments the
    ``telemetry_torn_lines`` counter and one stderr warning names the
    file. FileNotFoundError when no part of the stream exists."""
    import sys

    parts = stream_parts(path)
    if not parts:
        raise FileNotFoundError(path)
    out = []
    for part in parts:
        torn = 0
        with open(part) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    torn += 1
        if torn:
            registry().counter("telemetry_torn_lines").inc(torn)
            print(f"[telemetry] {part}: skipped {torn} unparseable "
                  f"line(s) (torn tail from a killed run?)",
                  file=sys.stderr)
    return out


# ---------------------------------------------------------------------------
# Live tail: follow a (possibly rotating) stream without drops or dups
# ---------------------------------------------------------------------------

class StreamFollower:
    """Incremental reader of a logical telemetry stream — the cockpit's
    and alert engine's ingest path.

    :meth:`poll` returns every record appended since the last poll, in
    order, across :class:`TelemetryRun` rotations: when the live file is
    renamed to ``{stem}.N.jsonl`` mid-tail, the follower finishes the
    rotated part from its remembered byte offset (same inode, so nothing
    is re-read) before moving to the new live file — no record is
    dropped and none is delivered twice. A partially-written final line
    stays buffered until its newline arrives (a mid-write poll must not
    mis-parse a half record); an unparseable *complete* line is skipped,
    matching :func:`read_records`.
    """

    def __init__(self, path: str):
        self.path = path
        # Lowest rotation-part index not yet fully consumed; parts below
        # it are done. 0 = consume every existing part from the start.
        self._part_cursor = 0
        self._ino: int | None = None     # inode of the file mid-read
        self._off = 0                    # bytes of it consumed
        self._buf = b""                  # partial trailing line

    def _reset_file(self) -> None:
        self._ino, self._off, self._buf = None, 0, b""

    def _drain(self, path: str, out: list[dict], *, final: bool) -> bool:
        """Read ``path`` from the remembered offset (reset when it is a
        different file than last time), appending parsed records.
        ``final``: the file can never grow again (a rotated part), so a
        buffered partial line is parse-attempted and then discarded.
        Returns False when the file vanished between listing and open."""
        try:
            with open(path, "rb") as f:
                ino = os.fstat(f.fileno()).st_ino
                if ino != self._ino:
                    self._ino, self._off, self._buf = ino, 0, b""
                f.seek(self._off)
                data = f.read()
        except OSError:
            return False
        self._off += len(data)
        buf = self._buf + data
        lines = buf.split(b"\n")
        self._buf = lines.pop()          # incomplete tail stays buffered
        if final and self._buf:
            lines.append(self._buf)      # a rotated part never grows —
            self._buf = b""              # parse-or-drop its last line
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                registry().counter("telemetry_torn_lines").inc()
        return True

    def poll(self) -> list[dict]:
        """Every record appended (to any part) since the last poll."""
        out: list[dict] = []
        stem, ext = os.path.splitext(self.path)
        for _ in range(10_000):          # re-list bound (rotation races)
            pending = [i for i in _part_indices(self.path)
                       if i >= self._part_cursor]
            if pending:
                # Oldest unconsumed part first. If it is the file we were
                # mid-reading as the live stream (rotation renamed it out
                # from under us), _drain continues at the same inode +
                # offset; otherwise it starts from byte 0.
                idx = pending[0]
                self._drain(f"{stem}.{idx}{ext}", out, final=True)
                self._part_cursor = idx + 1
                self._reset_file()
                continue
            # The live file. A rotation between the part listing above
            # and this read shows up as a changed inode — loop so the
            # now-rotated part is drained first.
            try:
                if (self._ino is not None
                        and os.stat(self.path).st_ino != self._ino):
                    continue
            except OSError:
                break                    # no live file (yet)
            self._drain(self.path, out, final=False)
            break
        return out


def follow_records(path: str, *, poll_s: float = 0.2,
                   stop: Callable[[], bool] | None = None):
    """Generator live-tailing a telemetry stream across rotations: yields
    each record once, in order, sleeping ``poll_s`` between empty polls.
    Runs forever unless ``stop()`` returns True — after which one final
    drain still yields everything written before the stop."""
    follower = StreamFollower(path)
    while True:
        recs = follower.poll()
        yield from recs
        if stop is not None and stop():
            yield from follower.poll()
            return
        if not recs:
            time.sleep(poll_s)
