"""SLO burn-rate alerts: declarative rules over the live telemetry.

The regression gate (utils/baseline.py) judges a *finished* run; an
operator watching a fleet needs the same judgment *while it runs*. This
module evaluates declarative rules against the records a campaign is
writing right now — fed either directly (:meth:`AlertEngine.observe`)
or by live-tailing streams across rotations
(:meth:`AlertEngine.watch` + :meth:`AlertEngine.poll`, built on
:class:`~.telemetry.StreamFollower`) — and emits **deduplicated typed
``alert`` records**: one ``firing`` record when a rule first breaches,
one ``resolved`` when it heals, never a record per evaluation.

Rules (each scoped per subject — per tenant for step/serve signals —
so one slow tenant cannot hide behind a fast fleet median):

* :class:`StepTimeDrift` — recent step-time p50 vs a reference: the
  baseline-ledger band when the run has history
  (:func:`step_time_reference_from_ledger`, the PR-11 ledger), else a
  self-baseline from the run's own first healthy window. Fires when
  ``p50 > max(ref * factor, ref + min_drift_s)`` (the absolute floor
  keeps millisecond CPU jitter from ever firing).
* :class:`BurnRate` — classic multiwindow burn rate over serve SLOs
  (``ttft_s`` / ``token_latency_s`` from per-request ``serve``
  records): the fraction of requests violating ``target_s``, divided
  by the error ``budget``, over a SHORT and a LONG window — firing
  only when **both** exceed ``burn`` (fast-burn detection that still
  ignores one bad request).
* :class:`GaugeCeiling` — a sustained level signal (page-pool
  occupancy from engine ``serve`` summaries / the live gauge feed)
  above a ceiling.
* :class:`HealthFloor` — any device-health score at/below a floor
  (fed by the orchestrator from the installed monitor).

Determinism: the engine takes its clock from the records (``now`` =
max observed ``ts``) unless the caller passes one — a replayed stream
produces the identical alert sequence.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from statistics import median
from typing import Any, Callable

from distributed_model_parallel_tpu.utils.telemetry import StreamFollower

__all__ = [
    "AlertEngine",
    "BurnRate",
    "GaugeCeiling",
    "HealthFloor",
    "StepTimeDrift",
    "default_rules",
    "step_time_reference_from_ledger",
]


def step_time_reference_from_ledger(path: str,
                                    key: str | None = None) -> float | None:
    """A step-time reference from the PR-11 baseline ledger
    (utils/baseline.py): the median ``step_time_p50_s`` over the last 8
    green entries (of ``key`` when given, any key otherwise). None when
    the ledger has no usable history — the drift rule then falls back
    to its self-baseline."""
    from distributed_model_parallel_tpu.utils.baseline import load_ledger

    vals = [e["metrics"]["step_time_p50_s"]
            for e in load_ledger(path)
            if e.get("green") and (key is None or e.get("key") == key)
            and isinstance((e.get("metrics") or {}).get("step_time_p50_s"),
                           (int, float))]
    return median(vals[-8:]) if vals else None


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StepTimeDrift:
    """Recent step-time p50 drifted above the reference band."""

    name: str = "step_time_drift"
    scope: str = "tenant"         # one state cell per tenant
    window: int = 4               # recent samples the p50 is taken over
    baseline_n: int = 4           # self-baseline: first N samples' median
    factor: float = 3.0           # fire when p50 > ref * factor ...
    min_drift_s: float = 0.05     # ... and p50 > ref + this (jitter floor)
    reference_s: float | None = None    # ledger band override

    def make_state(self) -> dict:
        return {"recent": deque(maxlen=self.window), "baseline": []}

    def observe(self, state: dict, rec: dict) -> None:
        if rec.get("kind") != "step":
            return
        t = rec.get("step_time_s")
        if not isinstance(t, (int, float)):
            return
        if (self.reference_s is None
                and len(state["baseline"]) < self.baseline_n):
            state["baseline"].append(float(t))
        state["recent"].append(float(t))

    def evaluate(self, state: dict, now: float,
                 signals: dict) -> tuple[bool, dict] | None:
        if len(state["recent"]) < state["recent"].maxlen:
            return None                       # not enough evidence yet
        ref = (self.reference_s if self.reference_s is not None
               else median(state["baseline"])
               if len(state["baseline"]) >= self.baseline_n else None)
        if ref is None:
            return None
        p50 = median(state["recent"])
        threshold = max(ref * self.factor, ref + self.min_drift_s)
        return p50 > threshold, {
            "value": round(p50, 6), "threshold": round(threshold, 6),
            "reference": round(ref, 6)}


@dataclasses.dataclass(frozen=True)
class BurnRate:
    """Serve-SLO burn rate over short + long windows."""

    metric: str = "ttft_s"        # per-request serve record key
    target_s: float = 1.0         # SLO: a request over this violates
    budget: float = 0.1           # tolerated violation fraction
    burn: float = 2.0             # fire when both windows burn > this
    short_s: float = 30.0         # short window (seconds of record ts)
    long_s: float = 300.0
    min_requests: int = 4         # evidence floor per window
    # Default name embeds the metric: two BurnRate rules (ttft +
    # token latency) must not collide on one engine state cell.
    name: str = ""
    scope: str = "tenant"

    def __post_init__(self):
        if not self.name:
            object.__setattr__(self, "name",
                               f"serve_burn_rate_{self.metric}")

    def make_state(self) -> dict:
        return {"samples": deque()}      # (ts, violated) pairs

    def observe(self, state: dict, rec: dict) -> None:
        if rec.get("kind") != "serve" or rec.get("event") != "completed":
            return
        v = rec.get(self.metric)
        ts = rec.get("ts")
        if isinstance(v, (int, float)) and isinstance(ts, (int, float)):
            state["samples"].append((float(ts), v > self.target_s))

    def _burn(self, samples, now: float, horizon: float) -> float | None:
        window = [bad for ts, bad in samples if now - ts <= horizon]
        if len(window) < self.min_requests:
            return None
        return (sum(window) / len(window)) / self.budget

    def evaluate(self, state: dict, now: float,
                 signals: dict) -> tuple[bool, dict] | None:
        samples = state["samples"]
        while samples and now - samples[0][0] > self.long_s:
            samples.popleft()
        short = self._burn(samples, now, self.short_s)
        long_ = self._burn(samples, now, self.long_s)
        if short is None or long_ is None:
            return None
        return (short > self.burn and long_ > self.burn), {
            "value": round(short, 4), "threshold": self.burn,
            "burn_long": round(long_, 4), "metric": self.metric,
            "target_s": self.target_s}


@dataclasses.dataclass(frozen=True)
class GaugeCeiling:
    """A level signal sustained above a ceiling (page-pool occupancy)."""

    signal: str = "page_occupancy"
    ceiling: float = 0.95
    name: str = "page_pool_saturation"
    scope: str = "global"

    def make_state(self) -> dict:
        return {"last": None}

    def observe(self, state: dict, rec: dict) -> None:
        # Engine summaries carry the occupancy aggregate; the live
        # signal feed (set_signal) overrides between records.
        if rec.get("kind") == "serve" and rec.get("event") == "summary":
            occ = rec.get(self.signal)
            v = occ.get("max") if isinstance(occ, dict) else occ
            if isinstance(v, (int, float)):
                state["last"] = float(v)

    def evaluate(self, state: dict, now: float,
                 signals: dict) -> tuple[bool, dict] | None:
        v = signals.get(self.signal, state["last"])
        if not isinstance(v, (int, float)):
            return None
        return v > self.ceiling, {"value": round(float(v), 4),
                                  "threshold": self.ceiling}


@dataclasses.dataclass(frozen=True)
class HealthFloor:
    """Any device-health score at/below the floor (fed from the
    installed DeviceHealthMonitor via ``set_signal('health_scores',
    monitor.snapshot()['scores'])``)."""

    floor: float = 0.5
    name: str = "device_health_floor"
    scope: str = "global"

    def make_state(self) -> dict:
        return {}

    def observe(self, state: dict, rec: dict) -> None:
        pass

    def evaluate(self, state: dict, now: float,
                 signals: dict) -> tuple[bool, dict] | None:
        scores = signals.get("health_scores")
        if not scores:
            return None
        worst_id, worst = min(scores.items(), key=lambda kv: kv[1])
        return worst <= self.floor, {
            "value": round(float(worst), 4), "threshold": self.floor,
            "device": worst_id}


def default_rules(*, ledger_path: str | None = None,
                  ledger_key: str | None = None) -> list:
    """The orchestrator's default rule set. With a ledger path, the
    drift rule anchors to the committed baseline band instead of the
    run's own first window."""
    ref = (step_time_reference_from_ledger(ledger_path, ledger_key)
           if ledger_path else None)
    return [
        StepTimeDrift(reference_s=ref),
        BurnRate(metric="ttft_s"),
        BurnRate(metric="token_latency_s", target_s=0.2),
        GaugeCeiling(),
        HealthFloor(),
    ]


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class AlertEngine:
    """Evaluates rules on a cadence and emits deduplicated typed
    ``alert`` records.

    Feed it records with :meth:`observe` (or :meth:`watch` + the
    :meth:`poll` live-tail), level signals with :meth:`set_signal`,
    then call :meth:`tick` each cadence: every state *transition*
    (healthy->firing, firing->resolved) is returned and written to
    ``sink`` (anything with ``.record``). ``firing`` lists the
    currently-firing alerts for statusz/cockpit surfacing."""

    def __init__(self, rules: list | None = None, *, sink=None):
        self.rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            # State cells are keyed by rule name: two rules sharing one
            # would corrupt each other's windows — no silent merges.
            raise ValueError(f"duplicate alert rule names {dupes}; give "
                             f"each rule a distinct name=")
        self.sink = sink
        self.signals: dict[str, Any] = {}
        self.events: list[dict] = []        # every transition ever emitted
        self._followers: dict[str, StreamFollower] = {}
        # (rule name, subject) -> {"state": rule state, "firing": bool}
        self._state: dict[tuple[str, str], dict] = {}
        self._max_ts = 0.0

    # -- ingest --------------------------------------------------------------
    def watch(self, path: str) -> None:
        """Live-tail ``path`` (idempotent; rotation-safe)."""
        if path not in self._followers:
            self._followers[path] = StreamFollower(path)

    def poll(self) -> int:
        """Drain every watched stream into the rule states; returns how
        many records were ingested."""
        n = 0
        for follower in self._followers.values():
            for rec in follower.poll():
                self.observe(rec)
                n += 1
        return n

    def observe(self, rec: dict) -> None:
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            self._max_ts = max(self._max_ts, ts)
        subject = str(rec.get("tenant") or "")
        for rule in self.rules:
            # Global rules (health floor, page ceiling) keep ONE state
            # cell; tenant-scoped ones (drift, burn rate) keep one per
            # stream subject so a slow tenant can't hide in the fleet.
            cell = self._cell(rule, subject if rule.scope == "tenant"
                              else "")
            rule.observe(cell["state"], rec)

    def set_signal(self, name: str, value: Any) -> None:
        """Push a level signal (health scores, live gauge values) for
        the next tick."""
        self.signals[name] = value

    # -- evaluation ----------------------------------------------------------
    def _cell(self, rule, subject: str) -> dict:
        key = (rule.name, subject)
        cell = self._state.get(key)
        if cell is None:
            cell = self._state[key] = {"state": rule.make_state(),
                                       "firing": False}
        return cell

    def tick(self, now: float | None = None) -> list[dict]:
        """One evaluation pass; returns (and records to the sink) the
        transitions. ``now`` defaults to the max record ts seen —
        deterministic under replay."""
        if now is None:
            now = self._max_ts
        for rule in self.rules:
            if rule.scope == "global":
                self._cell(rule, "")    # signal-fed rules need no records
        out: list[dict] = []
        for (rule_name, subject), cell in sorted(self._state.items()):
            rule = next((r for r in self.rules if r.name == rule_name),
                        None)
            if rule is None:
                continue
            verdict = rule.evaluate(cell["state"], now, self.signals)
            if verdict is None:
                continue
            breached, detail = verdict
            if breached and not cell["firing"]:
                cell["firing"] = True
                out.append({"rule": rule_name, "subject": subject,
                            "state": "firing", **detail})
            elif not breached and cell["firing"]:
                cell["firing"] = False
                out.append({"rule": rule_name, "subject": subject,
                            "state": "resolved", **detail})
        for ev in out:
            self.events.append(ev)
            if self.sink is not None:
                try:
                    self.sink.record("alert", **ev)
                except Exception:
                    pass
        return out

    @property
    def firing(self) -> list[dict]:
        """Currently-firing alerts: ``[{rule, subject}]``."""
        return [{"rule": k[0], "subject": k[1]}
                for k, cell in sorted(self._state.items())
                if cell["firing"]]
