"""In-process status/metrics exporter: ``/metrics``, ``/statusz``,
``/healthz`` over stdlib ``http.server`` on a daemon thread.

Everything the stack records today is post-hoc — JSONL streams parsed
after the run. This module is the *live* half of the observability
plane: one HTTP exporter per process (``DMP_STATUSZ_PORT`` or
``TrainConfig.statusz_port``; port 0 picks an ephemeral port) that any
component of the process registers a **status provider** with, so an
operator (or Prometheus, or ``scripts/dmp_top.py``) can ask a running
fleet what it is doing *now*:

* ``GET /metrics`` — Prometheus text exposition rendered from the live
  :class:`~.telemetry.MetricsRegistry`: counters (with per-tenant
  label series — the orchestrator's co-resident tenants scrape apart),
  gauges, and histograms as summary quantiles + ``_count``/``_sum``.
* ``GET /statusz`` — one JSON document: every registered provider's
  payload (trainers: run name / global step / current plan payload;
  the orchestrator: the tenant table with state/devices/attempt; the
  serving engine: queue depth / page occupancy), plus built-ins — the
  device-health sentinel's scores and quarantine set
  (:func:`~.health.installed`) and the open span stack of every thread
  (:func:`~.tracing.live_spans`).
* ``GET /healthz`` — 200 when healthy, 503 when any device is
  health-quarantined or any provider reports ``healthy: false`` (the
  trainers report their stall-watchdog state through this) — the
  liveness/readiness contract a fleet scheduler probes.

Opt-in and one-per-process: ``maybe_serve(port)`` starts the server the
first time a port is configured (explicit argument or the env var) and
afterwards returns the running server regardless of the argument —
orchestrated tenants register providers on the orchestrator's exporter
(tenants are labels/provider names, never ports). With neither
configured everything here is a no-op: no thread, no socket, no
provider registry growth (``register`` drops registrations when no
server runs).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from distributed_model_parallel_tpu.utils import health as _health
from distributed_model_parallel_tpu.utils import tracing as _tracing
from distributed_model_parallel_tpu.utils.telemetry import (
    Counter,
    Gauge,
    Histogram,
    registry,
)

__all__ = [
    "StatuszServer",
    "active",
    "maybe_serve",
    "prometheus_text",
    "register",
    "registered",
    "shutdown",
    "status_payload",
    "unregister",
]

_lock = threading.Lock()
_server: "StatuszServer | None" = None
_providers: dict[str, Callable[[], dict]] = {}


# ---------------------------------------------------------------------------
# Provider registry (process-wide, like the metrics registry)
# ---------------------------------------------------------------------------

def register(name: str, fn: Callable[[], dict]) -> bool:
    """Register (or replace — a re-admitted tenant rebuilds its trainer)
    a status provider: ``fn()`` returns a JSON-ready dict rendered under
    ``providers[name]`` in ``/statusz``. A payload carrying
    ``healthy: false`` flips ``/healthz`` to 503. No-op (returns False)
    when no exporter is running — an unexported process must not
    accumulate provider closures."""
    with _lock:
        if _server is None:
            return False
        _providers[str(name)] = fn
        return True


def unregister(name: str) -> None:
    with _lock:
        _providers.pop(str(name), None)


def registered() -> tuple[str, ...]:
    with _lock:
        return tuple(sorted(_providers))


def register_trainer(trainer, workload: str) -> bool:
    """One wiring call shared by all three trainers: register a
    ``/statusz`` provider reading the trainer's live state — run name,
    global step, current plan payload, slice devices, and the stall
    watchdog's health — named after the tenant when constructed inside
    a ``tenant_scope`` (the orchestrator's exporter shows tenants as
    provider names, never ports). No-op without a running exporter."""
    from distributed_model_parallel_tpu.utils.telemetry import (
        current_tenant,
    )

    name = current_tenant() or trainer.config.log_name

    def _status() -> dict:
        cfg = trainer.config
        plan = None
        try:
            from distributed_model_parallel_tpu.autotune.plan import (
                plan_payload,
            )

            plan = plan_payload(
                cfg.mesh, getattr(cfg, "strategy", workload),
                num_microbatches=getattr(cfg, "num_microbatches", 1))
        except Exception:
            pass
        guards = getattr(trainer, "guards", None)
        watchdog = getattr(guards, "stall", None)
        return {
            "workload": workload,
            "run": cfg.log_name,
            "global_step": int(getattr(trainer, "_global_step", 0)),
            "start_epoch": int(getattr(trainer, "start_epoch", 0)),
            "devices": list(getattr(trainer, "_device_ids", ())),
            "plan": plan,
            "healthy": not bool(getattr(watchdog, "stalled", False)),
        }

    return register(name, _status)


# ---------------------------------------------------------------------------
# Renderers (also used headless by tests and the flight recorder)
# ---------------------------------------------------------------------------

def _esc(v: object) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(tags: dict, **extra: str) -> str:
    items = {**tags, **extra}
    if not items:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(items.items()))
    return "{" + inner + "}"


def prometheus_text() -> str:
    """The live registry in Prometheus text exposition format (0.0.4).

    Counters render their fleet total plus one series per tenant bucket
    (label ``tenant``); gauges render when set; histograms render as
    summaries — ``{quantile="0.5|0.9|0.99"}`` series plus ``_count`` and
    ``_sum`` — matching the interpolated bucket quantiles the telemetry
    snapshot reports, PLUS true cumulative ``_bucket{le="..."}`` series
    (ending at ``le="+Inf"`` == ``_count``) so an external scraper can
    compute its own quantiles. Buckets that captured an exemplar (e.g. a
    request ``trace_id`` — serve/engine.py passes them on the TTFT /
    queue-wait / token-latency observations) carry an OpenMetrics-style
    exemplar suffix: ``... # {trace_id="..."} <value>``."""
    lines: list[str] = []
    seen_types: set[str] = set()
    for name, tags, metric in registry().items():
        if isinstance(metric, Counter):
            if name not in seen_types:
                seen_types.add(name)
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_labels(tags)} {metric.value:g}")
            for tenant, v in sorted(metric.by_tenant.items()):
                lines.append(f"{name}{_labels(tags, tenant=tenant)} {v:g}")
        elif isinstance(metric, Gauge):
            if metric.value is None:
                continue
            if name not in seen_types:
                seen_types.add(name)
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_labels(tags)} {metric.value:g}")
        elif isinstance(metric, Histogram):
            if name not in seen_types:
                seen_types.add(name)
                lines.append(f"# TYPE {name} summary")
            for q in (50, 90, 99):
                v = metric.percentile(q)
                if v is not None:
                    lines.append(
                        f"{name}{_labels(tags, quantile=str(q / 100))} "
                        f"{v:g}")
            lines.append(f"{name}_count{_labels(tags)} {metric.count}")
            lines.append(f"{name}_sum{_labels(tags)} {metric.sum:g}")
            # Cumulative buckets: counts[i] is the per-bucket tally for
            # le=bounds[i] (the trailing slot is the +Inf overflow), so
            # the running sum is the Prometheus-native cumulative form.
            cum = 0
            for i, bound in enumerate(metric.bounds):
                cum += metric.counts[i]
                line = (f"{name}_bucket"
                        f"{_labels(tags, le=f'{bound:g}')} {cum}")
                ex = metric.exemplars.get(i)
                if ex is not None:
                    line += f' # {{trace_id="{_esc(ex[0])}"}} {ex[1]:g}'
                lines.append(line)
            line = f"{name}_bucket{_labels(tags, le='+Inf')} {metric.count}"
            ex = metric.exemplars.get(len(metric.bounds))
            if ex is not None:
                line += f' # {{trace_id="{_esc(ex[0])}"}} {ex[1]:g}'
            lines.append(line)
    return "\n".join(lines) + "\n"


def status_payload() -> dict:
    """The ``/statusz`` JSON document (also dumped into postmortem
    bundles): provider payloads + the health and span built-ins."""
    import time

    with _lock:
        providers = dict(_providers)
    out: dict = {"ts": time.time(), "pid": os.getpid(),
                 "providers": {}}
    for name, fn in sorted(providers.items()):
        try:
            out["providers"][name] = fn()
        except Exception as e:   # a dying provider must not kill the page
            out["providers"][name] = {"error": f"{type(e).__name__}: {e}"}
    monitor = _health.installed()
    out["health"] = monitor.snapshot() if monitor is not None else None
    out["spans"] = _tracing.live_spans()
    return out


def health_verdict() -> tuple[bool, list[str]]:
    """(ok, reasons): unhealthy when the health sentinel has quarantined
    devices or any provider payload says ``healthy: false``."""
    reasons: list[str] = []
    monitor = _health.installed()
    if monitor is not None:
        quarantined = monitor.quarantined_ids
        if quarantined:
            reasons.append(f"devices {list(quarantined)} quarantined")
    with _lock:
        providers = dict(_providers)
    for name, fn in sorted(providers.items()):
        try:
            payload = fn()
        except Exception as e:
            reasons.append(f"provider {name} failed: {type(e).__name__}")
            continue
        if payload.get("healthy") is False:
            reasons.append(f"provider {name} unhealthy")
    return (not reasons), reasons


# ---------------------------------------------------------------------------
# The server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):      # no stderr per scrape
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):                        # noqa: N802 - stdlib API
        try:
            path = self.path.split("?")[0].rstrip("/") or "/"
            if path == "/metrics":
                self._send(200, prometheus_text().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/statusz":
                self._send(200, json.dumps(
                    status_payload(), default=str).encode("utf-8"),
                    "application/json")
            elif path in ("/healthz", "/"):
                ok, reasons = health_verdict()
                self._send(200 if ok else 503, json.dumps(
                    {"ok": ok, "reasons": reasons}).encode("utf-8"),
                    "application/json")
            else:
                self._send(404, b'{"error": "not found"}',
                           "application/json")
        except Exception:
            # A scrape must never take the process down; the socket may
            # already be half-closed (client timeout) — just drop it.
            try:
                self._send(500, b'{"error": "internal"}',
                           "application/json")
            except Exception:
                pass


class StatuszServer:
    """One exporter: a ThreadingHTTPServer on a daemon thread, bound to
    127.0.0.1 (observability is not an ingress surface)."""

    def __init__(self, port: int):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="dmp-statusz")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def maybe_serve(port: int | None = None) -> StatuszServer | None:
    """Start (or return) the process's exporter.

    Resolution: a server already running always wins (one exporter per
    process — orchestrated tenants land on the orchestrator's);
    otherwise an explicit ``port`` (0 = ephemeral), otherwise
    ``DMP_STATUSZ_PORT``; with neither, return None and touch nothing —
    the true no-op contract."""
    global _server
    with _lock:
        if _server is not None:
            return _server
        if port is None:
            env = os.environ.get("DMP_STATUSZ_PORT")
            if env is None or env == "":
                return None
            port = int(env)
        _server = StatuszServer(port)
        # Announce once — with port 0 (ephemeral) this line is the only
        # way an operator learns where to point the scrape/cockpit.
        import sys

        print(f"[statusz] exporter on {_server.url} "
              f"(/metrics /statusz /healthz)", file=sys.stderr)
        return _server


def active() -> StatuszServer | None:
    return _server


def shutdown() -> None:
    """Stop the exporter and clear the provider registry (tests; a
    process normally keeps its exporter for life)."""
    global _server
    with _lock:
        server, _server = _server, None
        _providers.clear()
    if server is not None:
        server.close()
