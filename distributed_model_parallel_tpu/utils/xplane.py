"""Hardware-profiler trace capture + analysis (no TensorBoard UI needed).

``jax.profiler`` writes XSpace protos (``*.xplane.pb``) containing REAL
device timelines — per-HLO-op start/duration measured by the TPU runtime,
not host wall clock and not XLA cost-analysis estimates. The reference's
observability is host-side ``time.time()`` deltas (``utils.py:41-74``);
this module is the TPU-native upgrade that closes the loop from "we think
this step is bandwidth-bound" to measured per-op device time
(VERDICT r4 weak #1: retire demand-side >1.0 ``hbm_frac_of_peak``
inferences in favor of hardware counters).

Usage::

    with trace_to("/tmp/trace") as d: run_steps()
    space = load_xspace(d)           # newest *.xplane.pb under d
    plane = device_plane(space)      # "/device:TPU:0"
    mods  = module_events(plane)     # compiled-module executions
    ops   = op_breakdown(plane)      # per-op device time, categorized

The proto schema (XSpace → XPlane → XLine → XEvent with stat key/value
pairs) is public TSL/OpenXLA; parsing uses the ``xplane_pb2`` bindings
shipped with the baked-in tensorflow wheel, with a graceful error when
absent.
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import os
import re
from collections import defaultdict
from typing import Iterable

import jax

_xplane_pb2 = None


class XplaneProtosUnavailable(ImportError):
    """The xplane_pb2 protobuf bindings are not importable.

    Subclasses ImportError so pre-existing ``except ImportError`` callers
    keep working; new callers (the CLI below, scripts/dmp_report.py) catch
    this specifically and print :data:`PROTO_HINT` instead of a traceback.
    """


PROTO_HINT = (
    "xplane trace analysis needs the xplane_pb2 protobuf bindings "
    "(tensorflow.tsl.profiler.protobuf.xplane_pb2, shipped with the "
    "tensorflow wheel); they are not importable here — install tensorflow "
    "(CPU build is enough) or skip the trace-analysis step; trace CAPTURE "
    "(jax.profiler / trace_to) works without them")


def _pb2():
    """Lazy import: tensorflow is heavy and only profiler analysis needs it."""
    global _xplane_pb2
    if _xplane_pb2 is None:
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
        except ImportError as e:        # pragma: no cover - env without tf
            raise XplaneProtosUnavailable(PROTO_HINT) from e
        _xplane_pb2 = xplane_pb2
    return _xplane_pb2


def protos_available() -> bool:
    """True when the xplane_pb2 bindings import (analysis paths will work)."""
    try:
        _pb2()
    except XplaneProtosUnavailable:
        return False
    return True


@contextlib.contextmanager
def trace_to(log_dir: str):
    """Capture a profiler trace; yields ``log_dir`` for later parsing."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def load_xspace(log_dir: str):
    """Parse the newest ``*.xplane.pb`` under ``log_dir`` into an XSpace."""
    paths = sorted(glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {log_dir}")
    xs = _pb2().XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def device_plane(space, index: int = 0):
    """The ``/device:TPU:<index>`` plane (raises if the trace is host-only,
    e.g. when the backend doesn't stream device events through the tunnel)."""
    name = f"/device:TPU:{index}"
    for plane in space.planes:
        if plane.name == name:
            return plane
    raise ValueError(
        f"no {name} plane in trace (planes: {[p.name for p in space.planes]})"
        " — device events were not captured")


def plane_peaks(plane) -> dict:
    """Device peaks the profiler itself reports (TFLOP/s, HBM GB/s…) —
    the hardware's own numbers, preferable to our static tables."""
    names = _stat_names(plane)
    out = {}
    for s in plane.stats:
        key = names.get(s.metadata_id, str(s.metadata_id))
        val = _stat_value(s)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[key] = val
    return out


def _stat_names(plane) -> dict:
    return {k: v.name for k, v in plane.stat_metadata.items()}


def _stat_value(s):
    """The set oneof value of an XStat — presence-checked, so a legitimate
    zero (e.g. device_offset_ps=0 for the first event) survives instead of
    falling through a truthiness chain to None."""
    which = s.WhichOneof("value")
    return getattr(s, which) if which else None


def _stat(ev, names: dict, name: str):
    for s in ev.stats:
        if names.get(s.metadata_id) == name:
            return _stat_value(s)
    return None


@dataclasses.dataclass
class ModuleEvent:
    name: str
    start_ps: int
    duration_ps: int


def _line(plane, line_name: str):
    for line in plane.lines:
        if line.name == line_name:
            return line
    return None


def module_events(plane) -> list[ModuleEvent]:
    """Compiled-module executions (one per dispatched program), device time."""
    line = _line(plane, "XLA Modules")
    if line is None:
        return []
    ev_names = {k: v.name for k, v in plane.event_metadata.items()}
    st_names = _stat_names(plane)
    out = []
    for ev in line.events:
        dur = _stat(ev, st_names, "device_duration_ps")
        off = _stat(ev, st_names, "device_offset_ps")
        dur = ev.duration_ps if dur is None else dur
        off = ev.offset_ps if off is None else off
        out.append(ModuleEvent(ev_names.get(ev.metadata_id, "?"),
                               int(off), int(dur)))
    out.sort(key=lambda m: m.start_ps)
    return out


# HLO-instruction-text → category. Fusions are opaque here ("%fusion.3 =
# ... calls=%fused_computation.3"); classify_fusions() resolves them
# against the optimized HLO text when provided.
_CATEGORY_PATTERNS = [
    ("convolution", r"\bconvolution\b"),
    ("matmul", r"\bdot\b|\bcustom-call.*__cublas|\bdot-general\b"),
    ("allreduce", r"\ball-reduce\b|\breduce-scatter\b|\ball-gather\b"
                  r"|\ball-to-all\b|\bcollective-permute\b"),
    ("copy", r"\bcopy\b|\bcopy-start\b|\bcopy-done\b|\btranspose\b"
             r"|\bbitcast\b|\breshape\b"),
    ("custom-call", r"\bcustom-call\b"),
    ("reduce", r"\breduce\b|\breduce-window\b"),
    ("loop-ctrl", r"\bwhile\b|\bconditional\b|\btuple\b"
                  r"|\bget-tuple-element\b"),
    ("infeed-outfeed", r"\binfeed\b|\boutfeed\b|\bsend\b|\brecv\b"),
]


def _category(op_text: str) -> str:
    if " fusion(" in op_text or op_text.startswith("%fusion"):
        return "fusion"
    for cat, pat in _CATEGORY_PATTERNS:
        if re.search(pat, op_text):
            return cat
    return "other"


_FUSION_CALL_RE = re.compile(r"calls=(%?[\w.\-]+)")


def fusion_kinds_from_hlo(hlo_text: str) -> dict[str, str]:
    """Map fused-computation name → dominant content category, from the
    optimized HLO module text (``compiled.as_text()``).

    A fusion containing a convolution is "conv-fusion"; containing a dot,
    "matmul-fusion"; a reduce, "reduce-fusion"; else "elementwise-fusion".
    This is how a flat fusion name in the trace becomes attributable work.
    """
    kinds: dict[str, str] = {}
    current = None
    body: list[str] = []

    def finish():
        if current is None:
            return
        text = "\n".join(body)
        if re.search(r"\bconvolution\b|= \S+ convolution", text):
            kinds[current] = "conv-fusion"
        elif re.search(r"\bdot\(|\bdot-general\b| dot\(", text):
            kinds[current] = "matmul-fusion"
        elif re.search(r"\breduce\(|\breduce-window\b", text):
            kinds[current] = "reduce-fusion"
        elif re.search(r"\bgather\(|\bscatter\(|dynamic-slice", text):
            kinds[current] = "gather-fusion"
        else:
            kinds[current] = "elementwise-fusion"

    for raw in hlo_text.splitlines():
        line = raw.strip()
        first = line.split("(")[0].split()[0] if line else ""
        if line.endswith("{") and first.lstrip("%").startswith("fused"):
            finish()
            current, body = first.lstrip("%"), []
        elif line == "}" and current is not None:
            finish()
            current, body = None, []
        elif current is not None:
            body.append(line)
    finish()
    return kinds


@dataclasses.dataclass
class OpRow:
    name: str          # leading HLO result name, e.g. "%fusion.12"
    category: str
    total_ps: int
    count: int
    example: str       # one full instruction text


def op_breakdown(plane, hlo_text: str | None = None) -> list[OpRow]:
    """Aggregate per-op device time over the whole trace, descending.

    With ``hlo_text`` (the compiled module's optimized HLO), fusion ops are
    re-categorized by their fused content (conv-fusion vs elementwise-…).
    """
    line = _line(plane, "XLA Ops")
    if line is None:
        return []
    ev_names = {k: v.name for k, v in plane.event_metadata.items()}
    st_names = _stat_names(plane)
    fusion_kinds = fusion_kinds_from_hlo(hlo_text) if hlo_text else {}
    agg: dict[str, list] = {}
    for ev in line.events:
        text = ev_names.get(ev.metadata_id, "?")
        dur = _stat(ev, st_names, "device_duration_ps")
        dur = int(ev.duration_ps if dur is None else dur)
        name = text.split(" ", 1)[0].rstrip("=").strip()
        cat = _category(text)
        if cat == "fusion" and fusion_kinds:
            m = _FUSION_CALL_RE.search(text)
            if m:
                cat = fusion_kinds.get(m.group(1).lstrip("%"), "fusion")
        if name not in agg:
            agg[name] = [cat, 0, 0, text]
        agg[name][1] += dur
        agg[name][2] += 1
    rows = [OpRow(n, c, t, k, ex) for n, (c, t, k, ex) in agg.items()]
    rows.sort(key=lambda r: -r.total_ps)
    return rows


def exclude_envelopes(rows: Iterable[OpRow]) -> list[OpRow]:
    """Drop loop/branch ENVELOPE ops (``%while``, ``%conditional``): their
    device duration contains every op executed inside the body, so summing
    them alongside the inner ops double-counts the entire loop. Use before
    category_totals or any roofline aggregation."""
    return [r for r in rows
            if not r.name.startswith(("%while", "%conditional"))]


def category_totals(rows: Iterable[OpRow]) -> dict[str, float]:
    """Device-time totals (seconds) per category, descending.

    Pass ``exclude_envelopes(rows)`` unless you want loop bodies counted
    twice (once inside the ``%while`` envelope, once as themselves)."""
    tot: dict[str, float] = defaultdict(float)
    for r in rows:
        tot[r.category] += r.total_ps / 1e12
    return dict(sorted(tot.items(), key=lambda kv: -kv[1]))


def main(argv=None) -> None:
    """CLI: summarize a jax.profiler trace directory without TensorBoard.

    ``python -m distributed_model_parallel_tpu.utils.xplane /tmp/trace``
    prints the module executions, per-category device time, and the top
    ops — the quick-look the reference's time.time() logging never had.
    """
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("trace_dir", help="directory passed to trace_to / "
                                     "jax.profiler.start_trace")
    p.add_argument("--top", type=int, default=15, help="top ops to print")
    args = p.parse_args(argv)

    try:
        _pb2()
    except XplaneProtosUnavailable as e:
        # Actionable one-liner, no traceback (VERDICT next #8).
        raise SystemExit(f"[xplane] {e}") from None
    plane = device_plane(load_xspace(args.trace_dir))
    peaks = plane_peaks(plane)
    mods = module_events(plane)
    rows = exclude_envelopes(op_breakdown(plane))
    print(f"device peaks: {peaks}")
    mod_s = sum(m.duration_ps for m in mods) / 1e12
    print(f"{len(mods)} module executions, {mod_s:.4f}s device time")
    for cat, sec in category_totals(rows).items():
        print(f"  {cat:24s} {sec * 1e3:10.2f} ms")
    print(f"top {args.top} ops:")
    for r in rows[:args.top]:
        print(f"  {r.total_ps / 1e9:9.3f} ms x{r.count:6d} "
              f"{r.category:18s} {r.name}")


if __name__ == "__main__":   # pragma: no cover - thin CLI shell
    main()
