"""Crash flight recorder: a telemetry ring buffer + postmortem bundles.

A crashed run's most valuable telemetry is the state *at the moment of
death* — the records just before it, what every thread was blocked on,
which spans were open, how full device memory was, which devices the
health sentinel distrusted. Post-hoc JSONL gives some of that; none of
it survives a wedged process or explains a hang. This module captures
it:

* :class:`FlightRecorder` — a bounded in-memory ring that
  :meth:`~.telemetry.TelemetryRun.record` tees every record into for
  free (one None-check when no recorder is installed; the tee happens
  *before* the disk write, so even the record a crash tears reaches the
  ring);
* :func:`dump_postmortem` — writes a timestamped bundle directory:

  ======================= =================================================
  file                    contents
  ======================= =================================================
  ``manifest.json``       reason, wall-clock ts, error, pid, file list
  ``records.jsonl``       the last-N telemetry records from the ring
  ``stacks.txt``          faulthandler-style stack of every live thread
                          (plus the failing exception's own traceback
                          when one is passed — the thread that died may
                          already be gone from the live set)
  ``spans.json``          every thread's open span stack
                          (:func:`~.tracing.live_spans`)
  ``memory.json``         :func:`~.telemetry.device_memory_snapshot`
  ``health.json``         the device-health sentinel's scores/quarantine
                          (:func:`~.health.installed`), when one is
                          installed
  ``journal.json``        the installed write-ahead request journal's
                          position + last-N raw lines
                          (``serve.journal.installed``), so a serving
                          crash bundle is self-contained for replay
                          debugging — null when no journal is installed
  ======================= =================================================

  and emits one typed ``postmortem`` telemetry record pointing at the
  bundle (fsync'd — see telemetry crash hygiene).

Triggers wired through the stack: the watchdog's stall escalation and
the supervisor's unrecovered exits (train/resilience.py), a killed
serving engine (serve/engine.py), an orchestrated tenant failing
(orchestrator/tenants.py), and the drivers' unhandled-exception hook
(:func:`install_excepthook`). Every trigger is a no-op unless a
recorder is installed — ``install_from_env()`` in the drivers makes
``DMP_FLIGHT_RECORDER=<bundle dir>`` (or ``1`` for ``./postmortem``)
the opt-in; the orchestrator takes a recorder directly.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any

from distributed_model_parallel_tpu.utils import telemetry, tracing
from distributed_model_parallel_tpu.utils import health as _health

__all__ = [
    "FlightRecorder",
    "dump_postmortem",
    "install",
    "install_excepthook",
    "install_from_env",
    "installed",
    "uninstall",
]

DEFAULT_CAPACITY = 512
DEFAULT_DIR = "./postmortem"


class FlightRecorder:
    """Bounded ring of the last ``capacity`` telemetry records.

    ``deque(maxlen=...)`` appends are atomic under the GIL, so the tee
    adds no locking to the record hot path; :meth:`records` snapshots
    under a lock only on the (rare) dump path."""

    def __init__(self, dir: str = DEFAULT_DIR,          # noqa: A002
                 capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.dir = dir
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.dumps: list[str] = []        # bundle paths written

    def observe(self, rec: dict) -> None:
        self._ring.append(rec)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)


_recorder: FlightRecorder | None = None


def install(recorder: FlightRecorder) -> FlightRecorder:
    """Install ``recorder`` process-wide and tee every TelemetryRun
    record into its ring (telemetry.set_record_tap)."""
    global _recorder
    _recorder = recorder
    telemetry.set_record_tap(recorder.observe)
    return recorder


def installed() -> FlightRecorder | None:
    return _recorder


def uninstall() -> None:
    global _recorder
    _recorder = None
    telemetry.set_record_tap(None)


def install_from_env() -> FlightRecorder | None:
    """Driver opt-in: ``DMP_FLIGHT_RECORDER=<dir>`` (or ``1``/``true``
    for ``./postmortem``) installs a recorder + the unhandled-exception
    hook. Returns the recorder, or None when the env var is unset (and
    touches nothing — the no-op contract)."""
    env = os.environ.get("DMP_FLIGHT_RECORDER")
    if not env:
        return None
    dir_ = DEFAULT_DIR if env.lower() in ("1", "true", "yes") else env
    cap = int(os.environ.get("DMP_FLIGHT_RECORDER_CAPACITY",
                             DEFAULT_CAPACITY))
    rec = install(FlightRecorder(dir=dir_, capacity=cap))
    install_excepthook()
    return rec


# ---------------------------------------------------------------------------
# Postmortem bundles
# ---------------------------------------------------------------------------

def _thread_stacks(error: BaseException | None) -> str:
    """Every live thread's stack, faulthandler-style but with thread
    names, plus the failing exception's traceback (its thread may
    already have unwound or died)."""
    out: list[str] = []
    if error is not None:
        out.append("=== failing exception ===")
        out.append("".join(traceback.format_exception(
            type(error), error, error.__traceback__)).rstrip())
        out.append("")
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(f"=== thread {names.get(ident, '?')} (ident {ident}) ===")
        out.append("".join(traceback.format_stack(frame)).rstrip())
        out.append("")
    return "\n".join(out)


_dump_lock = threading.Lock()
_dumping = False


def dump_postmortem(dir: str, reason: str, *,                # noqa: A002
                    telemetry_run=None,
                    error: BaseException | None = None,
                    records: list[dict] | None = None) -> str | None:
    """Write one postmortem bundle under ``dir`` and return its path.

    Never raises (a postmortem is observability, not control flow) and
    never recurses — a second dump racing the first (e.g. a stall
    escalation during a tenant failure) is skipped, not interleaved.
    ``records`` defaults to the installed recorder's ring (empty list
    when none). The typed ``postmortem`` record lands on
    ``telemetry_run`` when given."""
    global _dumping
    with _dump_lock:
        if _dumping:
            return None
        _dumping = True
    try:
        rec = _recorder
        if records is None:
            records = rec.records() if rec is not None else []
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:60]
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(dir, f"postmortem-{stamp}-{slug}")
        path = base
        i = 1
        while os.path.exists(path):
            path = f"{base}.{i}"
            i += 1
        os.makedirs(path, exist_ok=True)

        def _write(name: str, data: str) -> None:
            with open(os.path.join(path, name), "w") as f:
                f.write(data)

        _write("records.jsonl", "".join(
            json.dumps(r, default=str) + "\n" for r in records))
        _write("stacks.txt", _thread_stacks(error))
        _write("spans.json", json.dumps(tracing.live_spans(), indent=2,
                                        default=str))
        _write("memory.json", json.dumps(
            telemetry.device_memory_snapshot(), indent=2))
        monitor = _health.installed()
        _write("health.json", json.dumps(
            monitor.snapshot() if monitor is not None else None, indent=2))
        # Serving journal tail (serve/journal.py): imported lazily and
        # defensively — flightrec is wired into train-only processes
        # where the serve package may never load.
        try:
            from distributed_model_parallel_tpu.serve import (
                journal as _journal,
            )

            jr = _journal.installed()
        except Exception:
            jr = None
        _write("journal.json", json.dumps(
            {"path": jr.path, "position": jr.position(),
             "tail": jr.tail()} if jr is not None else None,
            indent=2, default=str))
        _write("manifest.json", json.dumps({
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "error": (f"{type(error).__name__}: {error}"[:500]
                      if error is not None else None),
            "n_records": len(records),
            "files": ["manifest.json", "records.jsonl", "stacks.txt",
                      "spans.json", "memory.json", "health.json",
                      "journal.json"],
        }, indent=2))
        telemetry.registry().counter("postmortem_dumps").inc()
        if rec is not None:
            rec.dumps.append(path)
        if telemetry_run is not None:
            try:
                telemetry_run.record(
                    "postmortem", reason=reason, bundle=path,
                    n_records=len(records),
                    error=(f"{type(error).__name__}: {error}"[:300]
                           if error is not None else None))
            except Exception:
                pass
        print(f"[flightrec] postmortem bundle written: {path}",
              file=sys.stderr)
        return path
    except Exception as e:       # pragma: no cover - best-effort path
        print(f"[flightrec] postmortem dump failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None
    finally:
        with _dump_lock:
            _dumping = False


def dump(reason: str, *, telemetry_run=None,
         error: BaseException | None = None) -> str | None:
    """Trigger-site entry point: dump a bundle into the installed
    recorder's directory. No-op (None) when no recorder is installed —
    every trigger in the stack calls through here, so an un-opted-in
    run pays exactly one None-check."""
    rec = _recorder
    if rec is None:
        return None
    return dump_postmortem(rec.dir, reason, telemetry_run=telemetry_run,
                           error=error)


# ---------------------------------------------------------------------------
# The drivers' unhandled-exception hook
# ---------------------------------------------------------------------------

_prev_excepthook = None


def install_excepthook() -> None:
    """Wrap ``sys.excepthook``: an unhandled exception in a driver
    first writes a fsync'd ``failure`` record to every live telemetry
    stream and closes them (``finish()`` — the final metrics/run_end
    records a crash would otherwise lose), dumps a postmortem bundle,
    then chains to the previous hook. Idempotent."""
    global _prev_excepthook
    if _prev_excepthook is not None:
        return
    _prev_excepthook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            runs = telemetry.live_runs()
            for run in runs:
                try:
                    run.failure("unhandled-exception",
                                detail=f"{exc_type.__name__}: {exc}"[:300])
                except Exception:
                    pass
            path = dump("unhandled-exception", error=exc)
            # The bundle pointer goes to EVERY live stream (a process can
            # hold several; live_runs() has no meaningful order).
            for run in runs:
                try:
                    if path is not None:
                        run.record("postmortem",
                                   reason="unhandled-exception",
                                   bundle=path,
                                   error=f"{exc_type.__name__}: "
                                         f"{exc}"[:300])
                    run.finish(error=f"{exc_type.__name__}"[:100])
                except Exception:
                    pass
        except Exception:
            pass
        _prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _hook


def uninstall_excepthook() -> None:
    global _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
