"""Resource metering: per-request cost attribution + utilization ledger.

The observability stack so far answers *where the time went* (spans,
rtrace phase partitions) but not *who consumed the capacity*: which
tenant's requests ate which chip-seconds, how much HBM page residency
each request reserved, and how busy each replica actually was between
the idle gaps and the brownout clamps. This module is that accounting
plane — the serving tier's billing meter, deliberately observation-only
(it must never perturb the schedule; the soak drill gates a
byte-identical schedule digest with metering on vs off).

Two ledgers per engine, one :class:`EngineMeter`:

* **Per-request bills.** A bill opens at residency start (scheduler
  admission, migration import, crash re-admission) and closes at
  residency end (terminal, or a drain/export hop). While open it
  accumulates:

  - *chip-seconds* — prefill chunks bill their full dispatch wall to
    the one request being prefilled (a chunk occupies the whole slice);
    decode/speculative rounds apportion the round's dispatch wall
    evenly across the live decode slots (one token per slot per round —
    equal shares of the batched matmul);
  - *page-seconds* — the request's page-pool reservation integrated
    over residency, with prefix-cache-shared pages credited at
    ``1/refcount`` (:meth:`~..serve.paged_kv.PagedKVCache.page_share`):
    a page three requests share costs each of them a third. Pages held
    only by the prefix cache itself are system overhead, billed to
    nobody;
  - *resident-seconds* — wall time the request held a slot at all.

  Closing a bill emits one typed ``meter`` record (:func:`emit_meter`)
  on the request's existing rtrace id. A migration emits a
  ``hop`` meter record on the source (billing that replica only for its
  own residency) and the destination opens a fresh bill, so the
  per-replica records chain by ``(trace, hop)`` — the terminal record
  is the LAST hop's. Terminal events (completed / shed / expired /
  failed) appear on exactly one meter record per trace, the invariant
  ``dmp_capacity --gate`` enforces. A replica that dies a hard death
  takes its open bills with it: a crashed residency is lost unbilled
  (under-billing is safe; phantom billing is not).

* **The utilization ledger.** Every engine iteration is classified into
  exactly one duty bucket — ``brownout`` (degraded-mode service,
  brownout level >= 1), ``busy`` (dispatched prefill or decode work),
  ``stalled`` (work exists but nothing dispatched: memory stalls,
  blocked admissions), ``idle`` (nothing to do) — and the iteration's
  measured wall sample is added to that bucket, so the buckets
  partition ``sum(engine._iter_s)`` *exactly by construction*. The
  fleet adds ``quarantined`` time for rounds a replica sat out of
  rotation (a quarantined engine never iterates, so it cannot classify
  itself). ``dmp_capacity --gate`` checks the partition against each
  replica's wall within 1%.

Timing is real-monotonic throughout (the same clock as
``Engine._iter_s``), even under a :class:`~..serve.traffic.SimClock` —
capacity is a statement about physical chip time, not virtual scenario
time. All metering bookkeeping self-times into :attr:`EngineMeter.write_s`
(the journal's ``write_s`` idiom) so the soak drill can gate metering
overhead at < 2% of serve-loop iteration time.

Registry metrics (cached handles — a registry lookup per emission is
measurable on the overhead budget): ``meter_records``,
``meter_chip_seconds``, ``meter_page_seconds`` counters here; the fleet
sets the ``serve_utilization_*`` duty-fraction gauges from the merged
ledgers (per-replica engines never write process-global gauges).

``serve/capacity.py`` + ``scripts/dmp_capacity.py`` turn the emitted
``meter`` / ``utilization`` records into the capacity report: per-tenant
cost tables, the fleet utilization timeline, sustainable tokens/s and
headroom per replica, and the what-if replica-count planner.
"""

from __future__ import annotations

import time

from distributed_model_parallel_tpu.utils.telemetry import registry

__all__ = [
    "EngineMeter",
    "LEDGER_BUCKETS",
    "METER_TERMINAL_EVENTS",
    "emit_meter",
]

# Duty-cycle buckets, in classification-priority order. Every engine
# iteration lands in exactly one of the first four; ``quarantined`` is
# fleet-added (a quarantined engine does not iterate).
LEDGER_BUCKETS = ("busy", "stalled", "brownout", "idle", "quarantined")

# Meter-record events that close a trace's billing — mirrors
# telemetry.RTRACE_TERMINAL_EVENTS; ``hop`` records (migration
# residency splits) are deliberately NOT terminal.
METER_TERMINAL_EVENTS = frozenset({"completed", "shed", "expired",
                                   "failed"})


def emit_meter(sink, req, event, *, replica=None, chip_s=0.0,
               page_s=0.0, resident_s=0.0, prefill_chunks=0,
               decode_rounds=0) -> None:
    """Write one typed ``meter`` record for ``req`` to ``sink``.

    The single emission path for billed (engine) and unbilled (fleet
    queue shed / rejection / dead-end failure) meter records, so every
    record carries the same shape: the request's trace id, rid, tenant,
    the billing replica, the event (terminal or ``hop``), the hop index
    (``req.migrations`` — hop records chain by it), and the cost
    figures. No-op without a sink. Registry counters are looked up per
    call here (fleet terminals are rare, off the iteration hot path);
    the hot path goes through :class:`EngineMeter`'s cached handles.
    """
    if sink is None:
        return
    sink.record("meter", trace=req.trace_id, request=req.rid,
                tenant=req.tenant, replica=replica, event=event,
                hop=req.migrations, chip_s=chip_s, page_s=page_s,
                resident_s=resident_s, prefill_chunks=prefill_chunks,
                decode_rounds=decode_rounds,
                tokens=len(req.generated),
                cached_tokens=req.cached_prompt_tokens)
    reg = registry()
    reg.counter("meter_records").inc()
    reg.counter("meter_chip_seconds").inc(max(0.0, chip_s))
    reg.counter("meter_page_seconds").inc(max(0.0, page_s))


class _Bill:
    """One open residency's accumulating cost figures."""

    __slots__ = ("chip_s", "page_s", "resident_s", "prefill_chunks",
                 "decode_rounds")

    def __init__(self):
        self.chip_s = 0.0
        self.page_s = 0.0
        self.resident_s = 0.0
        self.prefill_chunks = 0
        self.decode_rounds = 0


class EngineMeter:
    """Per-engine resource meter: request bills + utilization ledger.

    One per :class:`~..serve.engine.Engine` (constructed when metering
    is enabled). The engine drives it: :meth:`open_bill` at residency
    start, :meth:`bill_prefill` / :meth:`bill_decode` around dispatches,
    :meth:`tick` once per iteration (classification + page-second
    integration), :meth:`close_hop` on drain/export, :meth:`terminal`
    at the request's end. ``replica`` / ``cell`` label the emitted
    records (the fleet stamps ``cell`` after partitioning).
    """

    def __init__(self, *, replica: str | None = None,
                 cell: int | None = None):
        self.replica = replica
        self.cell = cell
        self._bills: dict[str, _Bill] = {}
        self.ledger: dict[str, float] = {b: 0.0 for b in LEDGER_BUCKETS}
        self.iterations = 0
        # Per-tenant cost rollup, folded at bill close: tenant ->
        # {requests, chip_s, page_s, resident_s, tokens, good_tokens,
        #  sheds}. ``requests`` counts terminals; hops add cost only.
        self.by_tenant: dict[str, dict] = {}
        # Monotonic seconds spent inside metering bookkeeping — the
        # numerator of the soak drill's < 2%-of-iteration-time gate.
        self.write_s = 0.0
        self._m_records = registry().counter("meter_records")
        self._m_chip = registry().counter("meter_chip_seconds")
        self._m_page = registry().counter("meter_page_seconds")

    # -- billing hooks (engine hot path) ------------------------------------

    def open_bill(self, rid: str) -> None:
        """Residency start: admission, migration import, or crash
        re-admission. Idempotent — re-opening an existing bill keeps
        its accumulated figures (a resumed prefill is one residency)."""
        t0 = time.monotonic()
        self._bills.setdefault(rid, _Bill())
        self.write_s += time.monotonic() - t0

    def bill_prefill(self, rid: str, dur_s: float) -> None:
        """One prefill-chunk dispatch: the whole dispatch wall bills to
        the one request being prefilled (the chunk owns the slice)."""
        t0 = time.monotonic()
        bill = self._bills.get(rid)
        if bill is not None:
            bill.chip_s += dur_s
            bill.prefill_chunks += 1
        self.write_s += time.monotonic() - t0

    def bill_decode(self, rids, dur_s: float) -> None:
        """One decode/spec round dispatch: the round's wall apportions
        evenly across the live decode slots it served."""
        t0 = time.monotonic()
        if rids:
            share = dur_s / len(rids)
            for rid in rids:
                bill = self._bills.get(rid)
                if bill is not None:
                    bill.chip_s += share
                    bill.decode_rounds += 1
        self.write_s += time.monotonic() - t0

    def tick(self, dt: float, *, progress: bool, brownout: bool,
             has_work: bool, cache=None) -> None:
        """Classify one iteration's wall sample ``dt`` into its duty
        bucket and integrate page-seconds/resident-seconds over every
        open bill. Called once per ``step_once`` with the SAME sample
        appended to ``_iter_s`` — that identity is what makes the duty
        buckets partition the engine's iteration wall exactly."""
        t0 = time.monotonic()
        if brownout:
            bucket = "brownout"
        elif progress:
            bucket = "busy"
        elif has_work:
            bucket = "stalled"
        else:
            bucket = "idle"
        self.ledger[bucket] += dt
        self.iterations += 1
        for rid, bill in self._bills.items():
            bill.resident_s += dt
            if cache is not None:
                bill.page_s += dt * cache.page_share(rid)
        self.write_s += time.monotonic() - t0

    # -- bill close ---------------------------------------------------------

    def _fold_tenant(self, req, bill, *, terminal: bool,
                     shed: bool = False, good_tokens: int = 0) -> None:
        row = self.by_tenant.setdefault(
            req.tenant or "-", {"requests": 0, "chip_s": 0.0,
                                "page_s": 0.0, "resident_s": 0.0,
                                "tokens": 0, "good_tokens": 0,
                                "sheds": 0})
        row["chip_s"] += bill.chip_s
        row["page_s"] += bill.page_s
        row["resident_s"] += bill.resident_s
        if terminal:
            row["requests"] += 1
            row["tokens"] += len(req.generated)
            row["good_tokens"] += good_tokens
            if shed:
                row["sheds"] += 1

    def close_hop(self, req, sink) -> None:
        """Residency end WITHOUT a terminal — a drain/export migration.
        Emits a ``hop`` meter record billing this replica only for its
        own residency; the destination opens a fresh bill and the
        records chain by ``(trace, hop)``."""
        t0 = time.monotonic()
        bill = self._bills.pop(req.rid, None)
        if bill is not None:
            self._fold_tenant(req, bill, terminal=False)
            self._emit(sink, req, "hop", bill)
        self.write_s += time.monotonic() - t0

    def terminal(self, req, event: str, sink, *,
                 good_tokens: int = 0) -> None:
        """The request's single terminal: close its bill (a zero bill
        when it never reached residency — queue sheds, rejections) and
        emit the one terminal meter record the capacity gate counts."""
        t0 = time.monotonic()
        bill = self._bills.pop(req.rid, None) or _Bill()
        self._fold_tenant(req, bill, terminal=True,
                          shed=event in ("shed", "expired"),
                          good_tokens=good_tokens)
        self._emit(sink, req, event, bill)
        self.write_s += time.monotonic() - t0

    def _emit(self, sink, req, event, bill) -> None:
        """Hot-path twin of :func:`emit_meter` using cached handles."""
        if sink is None:
            return
        sink.record("meter", trace=req.trace_id, request=req.rid,
                    tenant=req.tenant, replica=self.replica,
                    event=event, hop=req.migrations, chip_s=bill.chip_s,
                    page_s=bill.page_s, resident_s=bill.resident_s,
                    prefill_chunks=bill.prefill_chunks,
                    decode_rounds=bill.decode_rounds,
                    tokens=len(req.generated),
                    cached_tokens=req.cached_prompt_tokens)
        self._m_records.inc()
        self._m_chip.inc(max(0.0, bill.chip_s))
        self._m_page.inc(max(0.0, bill.page_s))

    # -- fleet integration --------------------------------------------------

    def add_quarantined(self, dt: float) -> None:
        """Fleet-added duty: wall a quarantined replica sat out of
        rotation (it never iterated, so it could not classify itself)."""
        self.ledger["quarantined"] += dt

    # -- rollups ------------------------------------------------------------

    def chip_s_total(self) -> float:
        """Chip-seconds billed so far (closed rollups + open bills)."""
        closed = sum(r["chip_s"] for r in self.by_tenant.values())
        return closed + sum(b.chip_s for b in self._bills.values())

    def utilization(self) -> dict:
        """The duty-cycle ledger: per-bucket seconds plus their sum
        (``wall_s`` — equals iteration wall + quarantined time by
        construction) and the iteration count."""
        out = {f"{b}_s": self.ledger[b] for b in LEDGER_BUCKETS}
        out["wall_s"] = sum(self.ledger.values())
        out["iterations"] = self.iterations
        return out

    def record_utilization(self, sink) -> None:
        """Emit one typed ``utilization`` record — the per-replica duty
        ledger the capacity report's timeline and partition gate read."""
        if sink is None:
            return
        sink.record("utilization", replica=self.replica, cell=self.cell,
                    meter_write_s=self.write_s, **self.utilization())

    def summary(self) -> dict:
        return {"utilization": self.utilization(),
                "by_tenant": {t: dict(r)
                              for t, r in sorted(self.by_tenant.items())},
                "open_bills": len(self._bills),
                "chip_s": self.chip_s_total(),
                "write_s": self.write_s}
