"""Typed configuration for the framework.

The reference scatters configuration across argparse defaults and inline
literals (and some flags are silently ignored — reference
``model_parallel.py:89-97`` re-hard-codes batch size 512 / 12 workers over the
``-b``/``-j`` flags; see SURVEY.md §1 "Notable coupling"). Here every knob
lives in one dataclass tree with no hidden hard-coding; entry scripts parse CLI
overrides into these dataclasses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. Axis sizes of 1 disable an axis.

    Replaces the reference's ``--world-size`` + ``mp.spawn`` + NCCL process
    group (``model_parallel.py:19-24,57,162``): on TPU the "backend choice" is
    mesh/axis configuration, not a transport plugin (SURVEY.md §2.4).
    """

    data: int = 1          # data-parallel axis ("dp")
    stage: int = 1         # pipeline-stage axis ("pp")
    model: int = 1         # tensor-parallel axis ("tp")
    seq: int = 1           # sequence/context-parallel axis ("sp")
    expert: int = 1        # expert-parallel axis ("ep"), reserved

    # Multi-host layout: how many of the `data` ways cross the DCN (slow,
    # host-to-host) boundary. Must divide `data`. With dcn_data > 1 the data
    # axis is laid out host-major — the dcn_data host granules are the outer
    # factor — so XLA decomposes the gradient allreduce hierarchically
    # (ICI-local reduce-scatter, small DCN exchange, ICI all-gather). Other
    # axes (stage/model/seq/expert) always stay within a host's ICI domain.
    dcn_data: int = 1

    # Axis names as they appear in PartitionSpecs / collectives.
    data_axis: str = "data"
    stage_axis: str = "stage"
    model_axis: str = "model"
    seq_axis: str = "seq"
    expert_axis: str = "expert"

    @property
    def num_devices(self) -> int:
        return self.data * self.stage * self.model * self.seq * self.expert

    def axis_sizes(self) -> dict[str, int]:
        return {
            self.data_axis: self.data,
            self.stage_axis: self.stage,
            self.model_axis: self.model,
            self.seq_axis: self.seq,
            self.expert_axis: self.expert,
        }


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """SGD + cosine annealing + linear warmup.

    Mirrors the reference's recipe: ``SGD(lr, momentum=0.9, weight_decay=1e-4)``
    + ``CosineAnnealingLR(T_max=90)`` + ``UntunedLinearWarmup`` over ~10 epochs
    (reference ``data_parallel.py:89-96``, ``model_parallel.py:105-108``).
    """

    name: str = "sgd"
    learning_rate: float = 0.4
    momentum: float = 0.9
    weight_decay: float = 1e-4
    nesterov: bool = False
    cosine_decay_steps: int | None = None   # if None: derived from epochs
    warmup_steps: int = 0
    grad_clip_norm: float | None = None
    # Gradient accumulation: average grads over k consecutive calls and apply
    # one optimizer update per k (optax.MultiSteps). A size-b batch at
    # accum_steps=k matches a size-k*b batch step exactly (mean-loss grads).
    accum_steps: int = 1
    # Exponential moving average of the weights (e.g. 0.999); evaluation and
    # best-acc selection use the averaged weights. None disables.
    ema_decay: float | None = None
    # Fused optimizer update (ops/pallas_optim.py): apply
    # SGD+momentum+weight-decay+LR in ONE Pallas TPU kernel over flat
    # coalesced parameter buckets instead of optax's per-leaf elementwise
    # op chain (pure-XLA fallback off-TPU, parity-tested against the optax
    # path). Only valid with name="sgd" — other optimizers reject it
    # loudly. Composes with grad_clip_norm and accum_steps; the LR
    # schedule stays a closure, so recovery-time lr_shrink rebuilds keep
    # the opt_state structure (docs/PERFORMANCE.md).
    fused: bool = False


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model selection + model-family specific knobs."""

    name: str = "mobilenetv2"               # registry key
    num_classes: int = 10
    # BatchNorm behavior: "local" = per-replica stats (nn.DataParallel / plain
    # DDP semantics), "sync" = cross-replica stats (SyncBatchNorm), "none" =
    # the no-BN variant (reference model/mobilenetv2.py:84-148).
    batchnorm: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: str = "float32"                  # compute dtype ("bfloat16" on TPU)
    param_dtype: str = "float32"
    extra: Mapping[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset + loader settings.

    The reference's transforms: random crop 32 pad 4, horizontal flip,
    normalize with CIFAR-10 stats (``data_parallel.py:31-40``); loaders bs 512
    train / 1000 test (``data_parallel.py:44-51``).
    """

    name: str = "cifar10"                   # registry key
    root: str = "./data"
    batch_size: int = 512
    eval_batch_size: int = 1000
    image_size: int = 32
    num_workers: int = 2
    shuffle: bool = True
    augment: bool = True
    seed: int = 0
    synthetic_ok: bool = True               # fall back to synthetic data offline
    synthetic_train_size: int = 2048
    synthetic_eval_size: int = 512
    # Native resolution of GENERATED synthetic images (None = image_size).
    # Set below image_size to exercise the on-device resize input stage the
    # way a real small-native dataset does (CIFAR pixels upsampled to a
    # 224px backbone, reference Readme.md:186-196).
    synthetic_native_size: int | None = None
    prefetch: int = 2                       # host-thread prefetch depth (0 = off)
    # Device-resident input prefetch (data/loader.DevicePrefetchLoader):
    # keep this many batches ahead of the consumed one already uploaded —
    # the sharded jax.device_put for batch k+1..k+depth is issued while
    # step k runs, so the step never waits on the host→device wire. 0
    # disables (the epoch loop falls back to a per-step device_put).
    # Composes with `prefetch` (host thread assembles, this stage
    # uploads); exact-resume semantics are untouched — the loader cursor
    # is consumer-driven (BatchLoader.position), and run-ahead uploads
    # are never counted as consumed (docs/PERFORMANCE.md).
    device_prefetch: int = 2
    use_native: bool = False                # C++ row-gather batch assembly
    # File-backed datasets (ImageFolder / CUB): True streams pixels from
    # disk per batch (host memory = the path list), False decodes the
    # whole split up front, None auto-picks by decoded size
    # (registry.LAZY_AUTO_BYTES) — the reference's torchvision loaders
    # are lazy the same way (dataset_collection.py:36-47).
    lazy_decode: bool | None = None


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """Automatic failure recovery (train/resilience.RecoverySupervisor).

    Default-off (``max_retries=0``): every detection keeps its historical
    fail-fast behavior. With ``max_retries > 0`` the supervisor maintains a
    per-epoch "last good" checkpoint slot and, on a non-finite loss/params
    detection (requires ``check_finite_every > 0``), restores it, optionally
    shrinks the learning rate, and retries the epoch — up to the budget.
    Restores verify the per-checkpoint integrity manifest and fall back to
    the previous committed version when the newest is torn
    (train/checkpoint.Checkpointer.restore ``allow_fallback``).
    """

    # Bounded retry budget for restore-and-resume recoveries; 0 disables the
    # supervisor (detections raise, as before).
    max_retries: int = 0
    # Multiply the learning rate by this factor on every non-finite recovery
    # (1.0 = keep it). Trainers that cannot rebuild their optimizer mid-run
    # reject values != 1.0 loudly — no silent ignores.
    lr_shrink: float = 1.0
    # Committed checkpoint versions retained per slot (Checkpointer keep-K):
    # >= 2 gives torn-newest restores something to fall back to.
    keep_checkpoints: int = 2
    # Escalate a stall-budget overrun (see TrainConfig.stall_budget_s) to a
    # graceful checkpoint-and-exit instead of only logging. The watchdog's
    # periodic "still blocked" lines appear either way.
    stall_exit: bool = False
    # Watchdog log cadence while a sync is blocked (None = budget/2, capped
    # to [0.05s, 30s]).
    watchdog_interval_s: float | None = None
    # Hard bound on a consistency check's blocking operations: the host
    # rendezvous before its cross-host collectives (multi-process runs)
    # AND the fingerprint fetch itself (any run, including single-process).
    # A wedged or missing participant then surfaces as a typed "straggler"
    # failure record + StragglerTimeoutError — fatal unless caught — instead
    # of hanging the very check meant to catch divergence (mesh.
    # barrier_with_timeout). Size it well above a slow-but-healthy
    # steady-state fetch; the FIRST check automatically gets a 10x grace
    # for one-time compile + cross-host compile skew. None = unbounded
    # (the stall watchdog still logs/escalates).
    barrier_timeout_s: float | None = None
    # Deterministic fault-injection plan (utils/faults.py): FaultSpec
    # entries or "kind@at[:param]" strings, e.g. ("nan_loss@1",). Empty =
    # no chaos.
    faults: Sequence[Any] = ()


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Top-level run configuration."""

    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    epochs: int = 100                       # reference data_parallel.py:160
    seed: int = 0
    # Data-parallel engine: "gspmd" = sharded jit (XLA infers the allreduce);
    # "ddp" = explicit shard_map per-replica programs with psum gradient
    # averaging and per-replica BatchNorm (parallel/ddp.py); "fsdp" = ZeRO-3
    # parameter+optimizer sharding over the data axis (parallel/fsdp.py);
    # "spmd_pipeline" = single-jit GPipe/1F1B over the stage axis
    # (parallel/spmd_cnn_pipeline.py); "auto" = cost-model-driven layout
    # (autotune/, docs/AUTOTUNE.md): probe the model, enumerate feasible
    # layouts of the LIVE device count, HBM-filter, rank with the
    # alpha-beta comm/compute model, rewrite strategy + mesh from the
    # winner and emit a typed `plan` telemetry record; elastic restarts
    # re-plan on the refitted mesh instead of blindly shrinking dp.
    strategy: str = "gspmd"
    ddp_bucket_bytes: int | None = None     # None = per-leaf psum
    ddp_allreduce: str = "psum"             # "psum" | "bucketed" | "ring"
    # Bucketed gradient allreduce cap in MiB — the DDP Reducer's
    # bucket_cap_mb knob (reference Readme.md:148-157). With
    # strategy="ddp" this routes the gradient averaging through
    # ops/collectives.bucketed_psum (reverse-leaf-order size-capped flat
    # buckets, so early buckets fire while the backward still runs and
    # XLA overlaps the collectives with compute). Only meaningful on the
    # explicit DDP path: the gspmd/fsdp strategies leave the reduction to
    # XLA's partitioner, so setting it there raises — no silent ignores.
    # Overrides ddp_bucket_bytes when both are set.
    grad_bucket_mb: float | None = None
    log_dir: str = "./log"
    log_name: str = "train"
    checkpoint_dir: str = "./checkpoint"
    resume: bool = False                    # reference data_parallel.py:21-22,80-87
    # Elastic resume (train/elastic.py): step-cadence "emergency" checkpoint
    # slot carrying the full resume state — train state, loader position
    # (epoch + batch cursor), global step, recovery budgets — so a
    # preempted run continues at the exact step instead of replaying the
    # epoch. The preemption save writes the same tree. 0 = only preemption/
    # epoch-boundary saves; N > 0 also saves every N steps. The slot is
    # distinct from the per-epoch best/good slots and exempt from their
    # keep-K rotation (per-slot retention, train/checkpoint.py).
    emergency_every: int = 0
    # On startup, shrink the mesh's data axis to the largest degree the
    # live device count and batch size allow (a preempted TPU job often
    # comes back on a degraded slice); resume then reshards the checkpoint
    # onto the new mesh (Checkpointer.restore_resharded). Non-data axes
    # never shrink — too few devices for them is still an error.
    elastic: bool = False
    # Asynchronous checkpointing: persist on a background thread so the next
    # epoch doesn't stall behind filesystem writes; fit() drains at the end.
    async_checkpoint: bool = False
    log_every_n_steps: int = 30             # reference data_parallel.py:116
    # Run the eval pass every N epochs (always on the final epoch). The
    # reference evals every epoch (data_parallel.py:160-172) — keep 1 for
    # parity; raise it when eval wall-clock dominates short epochs (e.g.
    # through a remote device tunnel where each eval batch pays an upload).
    eval_every: int = 1
    max_inflight_steps: int = 8             # bound on host run-ahead (async dispatch)
    # Numerical/stall guards (train/guards.py:GuardRunner): N > 0 checks
    # drained metrics for NaN/Inf at every sync and the full params every N
    # steps (raises NonFiniteError); stall_budget_s arms a wall-clock
    # watchdog around blocking drains (logs, never raises). Both close the
    # reference's silent-failure gap (SURVEY.md §5: a dead rank blocks
    # forever on dist.recv, distributed_layers.py:20).
    check_finite_every: int = 0
    stall_budget_s: float | None = None
    # Cross-replica consistency sentinel (train/consistency.py): every N
    # steps fingerprint params + optimizer state on device (per-leaf
    # finiteness / L2 / checksum), compare across the data-parallel axis,
    # and repair a minority-outlier replica in place by re-broadcasting
    # from a majority-good one (no quorum -> good-slot restore via the
    # recovery supervisor). 0 = off. Detects the silent data corruption
    # and replica drift the finiteness guards are blind to. Requires
    # replicated state: strategy "fsdp" (params sharded over data) rejects
    # it loudly.
    consistency_every: int = 0
    # Automatic recovery policy + fault-injection plan
    # (train/resilience.py, utils/faults.py). Off by default.
    recovery: RecoveryConfig = dataclasses.field(
        default_factory=RecoveryConfig)
    # Live status/metrics exporter (utils/statusz.py): serve /metrics
    # (Prometheus text), /statusz (JSON fleet state) and /healthz on
    # 127.0.0.1:<port> from a daemon thread (0 = ephemeral port). One
    # exporter per process — under the orchestrator the tenants register
    # providers on the fleet's exporter instead of opening their own.
    # None falls back to DMP_STATUSZ_PORT; unset both = true no-op.
    statusz_port: int | None = None
    # Device-resident fast path (gspmd strategy): upload the train set to the
    # accelerators once and run steps_per_dispatch train steps per jitted
    # program (lax.scan over on-device index gathers) — amortizes dispatch
    # overhead and removes per-step host->device image traffic.
    device_resident_data: bool = False
    steps_per_dispatch: int = 1
    # Pipeline-specific knobs (used when mesh.stage > 1).
    num_microbatches: int = 1               # 1 == reference's naive schedule
    stage_boundaries: Sequence[int] | None = None  # unit indices; None = balanced
    # Compute stage_boundaries from XLA per-unit FLOP costs (minimax
    # partition, parallel/auto_partition.py) instead of equal unit counts.
    auto_partition: bool = False
    pipeline_schedule: str = "gpipe"        # "gpipe" | "1f1b"
    virtual_stages: int = 1                 # >1 = Megatron interleaved chunks

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)
