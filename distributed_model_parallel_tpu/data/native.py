"""ctypes bindings for the native host data path (native/dmp_native.cpp).

Auto-builds the shared library with ``make`` on first use if a toolchain is
available; every entry point has a pure-numpy fallback so the framework works
without it (and tests assert native == numpy when it is available).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdmp_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.dmp_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.dmp_augment_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int]
        lib.dmp_normalize_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
        lib.dmp_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, idx: np.ndarray, *, n_threads: int = 4
                ) -> np.ndarray:
    """out[i] = src[idx[i]] over the leading axis (batch assembly)."""
    lib = _load()
    if lib is None:
        return src[idx]
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    item = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    lib.dmp_gather_rows(src.ctypes.data, idx.ctypes.data, out.ctypes.data,
                        len(idx), item, n_threads)
    return out


def augment_batch_host(images: np.ndarray, *, pad: int = 4, seed: int = 0,
                       n_threads: int = 4) -> np.ndarray:
    """Random pad-crop + h-flip on uint8 NHWC (numpy fallback is serial)."""
    assert images.dtype == np.uint8 and images.ndim == 4
    lib = _load()
    b, h, w, c = images.shape
    if lib is None:
        rng = np.random.default_rng(seed)
        padded = np.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        out = np.empty_like(images)
        for i in range(b):
            dy, dx = rng.integers(0, 2 * pad + 1, 2)
            img = padded[i, dy:dy + h, dx:dx + w]
            out[i] = img[:, ::-1] if rng.integers(2) else img
        return out
    images = np.ascontiguousarray(images)
    out = np.empty_like(images)
    lib.dmp_augment_batch(images.ctypes.data, out.ctypes.data, b, h, w, c,
                          pad, seed, n_threads)
    return out


def normalize_batch_host(images: np.ndarray, mean: np.ndarray,
                         std: np.ndarray, *, n_threads: int = 4) -> np.ndarray:
    """uint8 NHWC -> normalized float32 on the host."""
    assert images.dtype == np.uint8
    lib = _load()
    if lib is None:
        return ((images.astype(np.float32) / 255.0) - mean) / std
    images = np.ascontiguousarray(images)
    c = images.shape[-1]
    out = np.empty(images.shape, np.float32)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib.dmp_normalize_batch(images.ctypes.data, out.ctypes.data,
                            images.size // c, c,
                            mean.ctypes.data, std.ctypes.data, n_threads)
    return out
