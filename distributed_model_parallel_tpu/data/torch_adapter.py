"""Torch-dataset compatibility adapter.

The reference's data layer is torch/torchvision — ``ImageFolder``,
``torchvision.datasets.CIFAR10``, a custom pandas-joined ``CUBDataset``
(reference ``dataset/dataset_collection.py:28-69``) — so a user migrating from
it typically owns working ``torch.utils.data.Dataset`` objects. This module
lets those plug straight into the TPU framework: any map-style torch dataset
yielding ``(image, label)`` becomes an ``ArrayDataset`` (NHWC uint8 + int32
labels) usable by ``BatchLoader``, the device-resident fast path, and every
parallelism strategy.

Conversion happens once, up front (TPU training wants the host data path to
be trivial — the per-step work is index-gather, ``data/loader.py``), using
torch's own DataLoader workers for parallel decode. torch is imported lazily
so the framework has no hard torch dependency.
"""

from __future__ import annotations

import numpy as np

from distributed_model_parallel_tpu.data.registry import (
    ArrayDataset,
    CIFAR10_MEAN,
    CIFAR10_STD,
)


def _to_uint8_hwc(img) -> np.ndarray:
    """One sample -> (H, W, C) uint8, accepting the shapes torch datasets
    commonly yield: PIL images, HWC/CHW arrays or tensors, float [0,1]
    (the ToTensor convention) or uint8 [0,255], greyscale HW.

    Floats outside [0,1] are rejected rather than guessed at: a pipeline
    ending in ``transforms.Normalize`` would otherwise be clipped into
    garbage silently. Drop the Normalize — this framework normalizes
    on-device from the ``mean``/``std`` on the ArrayDataset.
    """
    arr = np.asarray(img)
    if arr.dtype == object:
        raise TypeError(f"cannot convert sample of type {type(img)!r}")
    if arr.ndim == 2:
        arr = arr[..., None]
    if arr.ndim != 3:
        raise ValueError(f"expected HW/HWC/CHW image, got shape {arr.shape}")
    # CHW (torchvision ToTensor) -> HWC. Channels-first is identified by a
    # leading dim of 1/3/4 with a trailing dim that is not channel-like.
    if arr.shape[0] in (1, 3, 4) and arr.shape[-1] not in (1, 3, 4):
        arr = np.moveaxis(arr, 0, -1)
    if arr.dtype != np.uint8:
        if np.issubdtype(arr.dtype, np.integer):
            # Wider integer types carrying ordinary [0,255] pixels.
            if arr.min() < 0 or arr.max() > 255:
                raise ValueError(
                    f"integer image values span [{arr.min()}, {arr.max()}]; "
                    f"expected [0, 255]")
            arr = arr.astype(np.uint8)
        else:
            arr = arr.astype(np.float64)
            if arr.min() < -1e-6 or arr.max() > 1.0 + 1e-6:
                raise ValueError(
                    f"float image values span [{arr.min():.3g}, "
                    f"{arr.max():.3g}]; expected the ToTensor [0,1] "
                    f"convention. If the torch pipeline ends in "
                    f"transforms.Normalize, remove it — normalization "
                    f"happens on-device from ArrayDataset.mean/std")
            arr = np.clip(np.round(arr * 255.0), 0, 255).astype(np.uint8)
    if arr.shape[-1] == 1:
        arr = np.repeat(arr, 3, axis=-1)
    if arr.shape[-1] != 3:
        raise ValueError(
            f"expected 1 or 3 channels, got {arr.shape[-1]} (shape "
            f"{arr.shape}); for RGBA sources add .convert('RGB') to the "
            f"dataset's loader/transform")
    return arr


def from_torch_dataset(dataset, *, num_classes: int | None = None,
                       mean=CIFAR10_MEAN, std=CIFAR10_STD,
                       num_workers: int = 0) -> ArrayDataset:
    """Materialize a map-style ``torch.utils.data.Dataset`` of
    ``(image, label)`` pairs into an ``ArrayDataset``.

    ``num_workers > 0`` decodes in parallel via ``torch.utils.data.DataLoader``
    (useful for ImageFolder-style on-the-fly JPEG decode); 0 iterates inline.
    ``num_classes`` defaults to ``max(label) + 1``.
    """
    import torch
    from torch.utils.data import DataLoader

    n = len(dataset)
    if n == 0:
        raise ValueError("torch dataset is empty")
    if num_workers > 0:
        loader = DataLoader(dataset, batch_size=None, num_workers=num_workers)
    else:
        # Index explicitly: bare iteration over a map-style Dataset only
        # stops on IndexError, which datasets backed by dict/list lookups
        # may never raise.
        loader = (dataset[i] for i in range(n))
    # The first sample fixes the shape; rows are written into one
    # preallocated (N, H, W, C) buffer so peak host memory is the dataset
    # itself, not dataset + per-sample list (matters at ImageNet scale).
    images = None
    labels = np.empty(n, np.int32)
    for i, (img, label) in enumerate(loader):
        row = _to_uint8_hwc(img)
        if images is None:
            images = np.empty((n,) + row.shape, np.uint8)
        elif row.shape != images.shape[1:]:
            raise ValueError(
                f"all samples must share one shape: sample {i} is "
                f"{row.shape}, expected {images.shape[1:]}; add a "
                f"Resize/CenterCrop transform to the torch dataset")
        images[i] = row
        labels[i] = int(label.item() if isinstance(label, torch.Tensor)
                        else label)
    return ArrayDataset(
        images=images,
        labels=labels,
        num_classes=(num_classes if num_classes is not None
                     else int(labels.max()) + 1),
        mean=np.asarray(mean, np.float32),
        std=np.asarray(std, np.float32))
