"""Host-side batching + on-device augmentation.

The reference pairs torchvision CPU transforms (random crop 32/pad 4, h-flip,
normalize; ``data_parallel.py:31-40``) with a multi-worker DataLoader
(``data_parallel.py:44-51``). The TPU-native design moves augmentation onto
the accelerator — `augment_batch` is pure jnp, fused by XLA into the train
step, leaving the host loop to shuffle indices and hand over uint8 batches
(cheap, bandwidth-friendly: normalization happens on-device so the wire
carries uint8, 4x less than float32).

Static shapes: the loader drops the last partial batch (`drop_last`
semantics), so every step compiles once.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.data.registry import ArrayDataset


class BatchLoader:
    """Epoch-shuffled uint8 batch iterator over an ArrayDataset.

    ``use_native=True`` assembles batches with the C++ row-gather
    (data/native.py); falls back to numpy fancy indexing transparently.

    Shuffle order is **stateless**: epoch ``e``'s permutation is derived
    from ``default_rng((seed, e))``, never from a consumed rng stream —
    epoch N's batch order is identical whether or not epochs 0..N-1 were
    ever iterated. That makes the loader's position a two-integer resume
    state (``state_dict``/``load_state_dict``: epoch + batch cursor), the
    property elastic resume (train/elastic.py) is built on: a run killed
    mid-epoch restarts at the exact next batch with nothing replayed or
    skipped.

    Position protocol: iteration itself never moves the persistent cursor
    (with a PrefetchLoader in front, the producer runs ahead of what the
    trainer actually consumed) except at clean exhaustion, which advances
    to the next epoch. The epoch drivers call :meth:`set_epoch` at epoch
    start and :meth:`position` after each *consumed* batch, so the cursor
    always reflects training progress, not prefetch progress.
    """

    def __init__(self, ds: ArrayDataset, batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 use_native: bool = False, num_workers: int = 4,
                 shard_by_process: bool = False):
        if batch_size > len(ds):
            raise ValueError(
                f"batch size {batch_size} exceeds dataset size {len(ds)}")
        self.ds = ds
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.use_native = use_native
        self.num_workers = num_workers
        self.seed = seed
        self._epoch = 0
        self._cursor = 0          # batches of self._epoch already consumed
        # Multi-process feeding: every process draws the *same* global batch
        # order (the rng seed is config-fixed, so permutations agree), but
        # materializes only its contiguous slice of each batch — the local
        # shard ``mesh.host_local_batch_to_global`` stitches into the global
        # array. Mirrors the per-rank DistributedSampler role in the
        # reference's multi-process runs (model_parallel.py:89-97).
        self.process_index = jax.process_index() if shard_by_process else 0
        self.process_count = jax.process_count() if shard_by_process else 1
        if batch_size % self.process_count:
            raise ValueError(
                f"batch size {batch_size} not divisible by process count "
                f"{self.process_count}")

    def __len__(self) -> int:
        n = len(self.ds)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    # -- resume position ----------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def cursor(self) -> int:
        return self._cursor

    def set_epoch(self, epoch: int) -> None:
        """Position at the start of ``epoch`` — unless already positioned
        *inside* that epoch (a mid-epoch ``load_state_dict``), in which
        case the loaded cursor is preserved. Epoch drivers call this at
        the top of every training epoch."""
        if epoch != self._epoch:
            self._epoch, self._cursor = int(epoch), 0

    def position(self, epoch: int, batch_cursor: int) -> None:
        """Authoritative position update from the consumer: ``batch_cursor``
        batches of ``epoch`` have been consumed. Called by the epoch
        drivers after each dispatched step — the iterator cannot track this
        itself because a PrefetchLoader produces ahead of consumption."""
        self._epoch, self._cursor = int(epoch), int(batch_cursor)

    def state_dict(self) -> dict:
        """Resume state. A fully-consumed epoch is normalized to the start
        of the next one, so "end of epoch e" and "start of epoch e+1" are
        the same position."""
        ep, cur = self._epoch, self._cursor
        if cur >= len(self):
            ep, cur = ep + 1, 0
        return {"epoch": int(ep), "batch_cursor": int(cur)}

    def load_state_dict(self, state: Mapping) -> None:
        ep, cur = int(state["epoch"]), int(state["batch_cursor"])
        if ep < 0 or cur < 0 or cur > len(self):
            raise ValueError(
                f"invalid loader state epoch={ep} batch_cursor={cur} "
                f"(epoch has {len(self)} batches)")
        if cur >= len(self):
            ep, cur = ep + 1, 0
        self._epoch, self._cursor = ep, cur

    def epoch_indices(self, epoch: int | None = None) -> np.ndarray:
        """The (possibly shuffled) sample order for ``epoch`` (default: the
        current position's epoch). Shared by the materializing iterator
        below and the device-resident fast path (train/trainer.py), so both
        see identical batch composition. Stateless: derived from
        ``(seed, epoch)`` only."""
        n = len(self.ds)
        if not self.shuffle:
            return np.arange(n)
        e = self._epoch if epoch is None else int(epoch)
        return np.random.default_rng((self.seed, e)).permutation(n)

    def _local_slice(self, sel: np.ndarray) -> np.ndarray:
        """This process's contiguous rows of one global batch's indices."""
        if self.process_count == 1:
            return sel
        if len(sel) % self.process_count:
            # Only reachable on a drop_last=False final partial batch (the
            # constructor validates batch_size itself): silently flooring
            # would drop samples and break the "same global batch stream as
            # single-process" invariant.
            raise ValueError(
                f"partial batch of {len(sel)} rows not divisible by "
                f"process count {self.process_count}; use drop_last=True "
                f"or pad the dataset")
        local = len(sel) // self.process_count
        return sel[self.process_index * local:(self.process_index + 1) * local]

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        n = len(self.ds)
        epoch, start = self._epoch, self._cursor
        idx = self.epoch_indices(epoch)
        stop = (n // self.batch_size) * self.batch_size if self.drop_last else n
        # The native row-gather operates on materialized arrays; for a lazy
        # (file-backed) dataset, fancy indexing IS the batch decode
        # (LazyImageArray thread pool), so use_native does not apply.
        if self.use_native and not getattr(self.ds, "is_lazy", False):
            from distributed_model_parallel_tpu.data import native
            for lo in range(start * self.batch_size, stop, self.batch_size):
                sel = self._local_slice(idx[lo:lo + self.batch_size])
                yield (native.gather_rows(self.ds.images, sel,
                                          n_threads=self.num_workers),
                       self.ds.labels[sel])
        else:
            for lo in range(start * self.batch_size, stop, self.batch_size):
                sel = self._local_slice(idx[lo:lo + self.batch_size])
                yield self.ds.images[sel], self.ds.labels[sel]
        # Clean exhaustion: advance to the next epoch, so a plain
        # for-each-epoch consumer (benchmarks) reshuffles per epoch without
        # calling set_epoch. Abandoned iterations never reach this line —
        # the consumer's position() calls stay authoritative.
        if epoch == self._epoch and start == self._cursor:
            self._epoch, self._cursor = epoch + 1, 0


class PrefetchLoader:
    """Background-thread prefetch over any batch iterable — the capability of
    the reference's ``num_workers``/pinned-memory DataLoader settings
    (``data_parallel.py:44-51``) in single-controller form: batch k+1 is
    assembled on a host thread while the accelerator runs batch k.

    Shutdown/failure contract (the preemption path depends on it):

    * a consumer that **abandons** iteration mid-epoch (preemption break,
      exception in the train step) signals the worker immediately and waits
      only ``join_timeout_s`` for it — a worker wedged inside the underlying
      loader (slow disk, dead NFS) is left behind as a daemon instead of
      hanging the trainer's graceful checkpoint-and-exit;
    * a worker **exception** propagates to the consumer (after any batches
      already buffered), and a worker that dies without managing to enqueue
      its sentinel is detected by liveness-checking ``get`` — the consumer
      raises instead of blocking forever.
    """

    def __init__(self, loader: Iterable, depth: int = 2, *,
                 join_timeout_s: float = 5.0):
        self.loader = loader
        self.depth = depth
        self.join_timeout_s = join_timeout_s

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        sentinel = object()
        stop = threading.Event()
        err: list[BaseException] = []

        def put(item) -> bool:
            # Bounded-wait put so the worker can never be stranded if the
            # consumer abandons the loop mid-epoch (exception in the train
            # step, KeyboardInterrupt, ...).
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            it = iter(self.loader)
            try:
                for item in it:
                    if not put(item):
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                # Propagate the abandon to the SOURCE: a generator-backed
                # loader gets its close()/GeneratorExit now (releasing file
                # handles, decode pools), not at some later GC.
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:   # noqa: BLE001 - already shutting down
                        pass
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True,
                             name="dmp-prefetch")
        t.start()
        try:
            while True:
                try:
                    item = q.get(timeout=0.5)
                except queue.Empty:
                    # Liveness check: a worker that died without enqueueing
                    # its sentinel (killed thread, interpreter teardown)
                    # must not leave the consumer blocked forever. The
                    # worker may also have enqueued its final item/sentinel
                    # and exited BETWEEN our timeout and this check — drain
                    # before declaring it dead (TOCTOU).
                    if not t.is_alive():
                        try:
                            item = q.get_nowait()
                        except queue.Empty:
                            if err:
                                raise err[0]
                            raise RuntimeError(
                                "prefetch worker died without a result "
                                "or sentinel") from None
                        if item is sentinel:
                            break
                        yield item
                    continue
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            # Bounded join: the worker observes `stop` within one put poll
            # (~0.1s) unless it is wedged inside the underlying loader
            # itself — in that case it stays behind as a daemon thread
            # rather than blocking the consumer's exit path (the preemption
            # checkpoint must not wait on a dead disk).
            t.join(self.join_timeout_s)
            if err:
                raise err[0]


def maybe_prefetch(loader: Iterable, depth: int) -> Iterable:
    """Wrap ``loader`` in a PrefetchLoader when ``depth > 0`` (else as-is)."""
    return PrefetchLoader(loader, depth=depth) if depth > 0 else loader


class DevicePrefetchLoader:
    """Device-resident double-buffered input prefetch.

    Wraps a host batch iterable and eagerly issues ``put_fn`` (the sharded
    ``jax.device_put`` — e.g. ``Trainer._shard_batch``) for the next
    ``depth`` batches while the consumer's current step runs, so at every
    yield up to ``depth`` future batches are already in flight to (or
    resident on) the accelerators. ``jax.device_put`` enqueues the
    transfer asynchronously, so run-ahead here IS compute/H2D overlap —
    no extra thread needed on top of the host-side :class:`PrefetchLoader`
    (which overlaps batch *assembly*; this stage overlaps the *upload*).

    Resume semantics are untouched by design: the persistent loader cursor
    is consumer-driven (``BatchLoader.position`` called by the epoch
    drivers per *consumed* batch), so run-ahead uploads are never counted
    as consumed — a kill mid-epoch resumes at the exact next batch the
    trainer dispatched, bitwise-identically (tests/test_perf_pipeline.py).

    Abandoning iteration mid-epoch (preemption break, train-step
    exception) closes the underlying iterator, propagating the shutdown
    to a PrefetchLoader worker / generator source. Per-iteration transfer
    stats land in :attr:`last_stats` (``puts`` issued, ``max_lead`` =
    the largest number of uploaded-but-unconsumed batches observed) — the
    no-silent-fallback proof bench.py's ``step_phase`` record carries.
    """

    def __init__(self, loader: Iterable, put_fn, depth: int = 2):
        if depth < 1:
            raise ValueError(f"device prefetch depth must be >= 1, "
                             f"got {depth}")
        self.loader = loader
        self.put_fn = put_fn
        self.depth = depth
        self.last_stats = {"puts": 0, "max_lead": 0}

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        stats = {"puts": 0, "max_lead": 0}
        self.last_stats = stats
        it = iter(self.loader)
        buf: list = []          # uploaded, not yet consumed (FIFO)
        exhausted = False
        try:
            while True:
                while not exhausted and len(buf) <= self.depth:
                    try:
                        batch = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    buf.append(self.put_fn(*batch))
                    stats["puts"] += 1
                if not buf:
                    return
                # Lead = batches in flight beyond the one about to be
                # consumed; the smoke test pins this at >= depth.
                stats["max_lead"] = max(stats["max_lead"], len(buf) - 1)
                yield buf.pop(0)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:   # noqa: BLE001 - already shutting down
                    pass


def maybe_device_prefetch(loader: Iterable, put_fn, depth: int) -> Iterable:
    """Wrap ``loader`` so it yields device-resident batches: a
    :class:`DevicePrefetchLoader` when ``depth > 0``, else a plain
    per-batch ``put_fn`` map (the historical per-step device_put)."""
    if depth > 0:
        return DevicePrefetchLoader(loader, put_fn, depth=depth)
    return (put_fn(*batch) for batch in loader)


def resolve_input_size(images_shape, image_size: int) -> tuple[int | None, int]:
    """(resize_to, input_hw) for the on-device resize input stage.

    ``resize_to`` is None when the configured ``image_size`` already matches
    the dataset's native resolution (no resize step compiled in). Shared by
    the DP and pipeline trainers so the squareness assumption is validated
    in exactly one place (ADVICE r2: comparing height alone would silently
    skip the resize for a non-square dataset whose height matches).
    """
    native_h, native_w = images_shape[1:3]
    if native_h != native_w:
        raise ValueError(
            f"the resize/input path assumes square images; dataset is "
            f"{native_h}x{native_w} — pre-crop it square")
    resize_to = image_size if image_size != native_h else None
    return resize_to, (resize_to or native_h)


def resize_batch(images_u8: jnp.ndarray, size: int) -> jnp.ndarray:
    """On-device bilinear resize NHWC uint8 -> (B, size, size, C) uint8.

    The input stage the reference's 224px finetune recipe needs
    (``Readme.md:186-196``: CIFAR images upsampled to the pretrained
    backbone's native resolution). Runs on the accelerator inside the train
    step — the wire still carries the small native-size uint8 batch, and
    XLA fuses the upsample with augmentation/normalization.
    """
    b, h, w, c = images_u8.shape
    if (h, w) == (size, size):
        return images_u8
    x = jax.image.resize(images_u8.astype(jnp.float32), (b, size, size, c),
                         method="bilinear")
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)


def normalize(images_u8: jnp.ndarray, mean: np.ndarray, std: np.ndarray,
              dtype=jnp.float32) -> jnp.ndarray:
    """uint8 NHWC -> normalized float (on device)."""
    x = images_u8.astype(dtype) / jnp.asarray(255.0, dtype)
    return (x - jnp.asarray(mean, dtype)) / jnp.asarray(std, dtype)


def augment_batch(rng: jax.Array, images_u8: jnp.ndarray, *, pad: int = 4,
                  flip: bool = True) -> jnp.ndarray:
    """Random crop (pad-and-crop) + horizontal flip, vectorized on device.

    Equivalent to the reference's ``RandomCrop(32, padding=4)`` +
    ``RandomHorizontalFlip`` (``data_parallel.py:33-35``). The crop is two
    batched ``take_along_axis`` gathers (rows then columns) rather than a
    vmapped ``dynamic_slice`` — the per-image dynamic-slice form lowers to
    a pathological scatter/gather on TPU (~20x slower, measured on v5e).
    uint8 in, uint8 out.
    """
    b, h, w, c = images_u8.shape
    rng_crop, rng_flip = jax.random.split(rng)
    padded = jnp.pad(images_u8, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                     mode="constant")
    offs = jax.random.randint(rng_crop, (b, 2), 0, 2 * pad + 1)
    rows = offs[:, 0][:, None] + jnp.arange(h)[None, :]        # [B, H]
    cols = offs[:, 1][:, None] + jnp.arange(w)[None, :]        # [B, W]
    out = jnp.take_along_axis(padded, rows[:, :, None, None], axis=1)
    out = jnp.take_along_axis(out, cols[:, None, :, None], axis=2)
    if flip:
        do_flip = jax.random.bernoulli(rng_flip, 0.5, (b,))
        out = jnp.where(do_flip[:, None, None, None], out[:, :, ::-1, :], out)
    return out
