"""Host-side batching + on-device augmentation.

The reference pairs torchvision CPU transforms (random crop 32/pad 4, h-flip,
normalize; ``data_parallel.py:31-40``) with a multi-worker DataLoader
(``data_parallel.py:44-51``). The TPU-native design moves augmentation onto
the accelerator — `augment_batch` is pure jnp, fused by XLA into the train
step, leaving the host loop to shuffle indices and hand over uint8 batches
(cheap, bandwidth-friendly: normalization happens on-device so the wire
carries uint8, 4x less than float32).

Static shapes: the loader drops the last partial batch (`drop_last`
semantics), so every step compiles once.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.data.registry import ArrayDataset


class BatchLoader:
    """Epoch-shuffled uint8 batch iterator over an ArrayDataset.

    ``use_native=True`` assembles batches with the C++ row-gather
    (data/native.py); falls back to numpy fancy indexing transparently.
    """

    def __init__(self, ds: ArrayDataset, batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 use_native: bool = False, num_workers: int = 4,
                 shard_by_process: bool = False):
        if batch_size > len(ds):
            raise ValueError(
                f"batch size {batch_size} exceeds dataset size {len(ds)}")
        self.ds = ds
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.use_native = use_native
        self.num_workers = num_workers
        self._rng = np.random.default_rng(seed)
        # Multi-process feeding: every process draws the *same* global batch
        # order (the rng seed is config-fixed, so permutations agree), but
        # materializes only its contiguous slice of each batch — the local
        # shard ``mesh.host_local_batch_to_global`` stitches into the global
        # array. Mirrors the per-rank DistributedSampler role in the
        # reference's multi-process runs (model_parallel.py:89-97).
        self.process_index = jax.process_index() if shard_by_process else 0
        self.process_count = jax.process_count() if shard_by_process else 1
        if batch_size % self.process_count:
            raise ValueError(
                f"batch size {batch_size} not divisible by process count "
                f"{self.process_count}")

    def __len__(self) -> int:
        n = len(self.ds)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch_indices(self) -> np.ndarray:
        """The (possibly shuffled) sample order for the next epoch. Shared
        by the materializing iterator below and the device-resident fast
        path (train/trainer.py), so both see identical batch composition."""
        n = len(self.ds)
        return self._rng.permutation(n) if self.shuffle else np.arange(n)

    def _local_slice(self, sel: np.ndarray) -> np.ndarray:
        """This process's contiguous rows of one global batch's indices."""
        if self.process_count == 1:
            return sel
        if len(sel) % self.process_count:
            # Only reachable on a drop_last=False final partial batch (the
            # constructor validates batch_size itself): silently flooring
            # would drop samples and break the "same global batch stream as
            # single-process" invariant.
            raise ValueError(
                f"partial batch of {len(sel)} rows not divisible by "
                f"process count {self.process_count}; use drop_last=True "
                f"or pad the dataset")
        local = len(sel) // self.process_count
        return sel[self.process_index * local:(self.process_index + 1) * local]

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        n = len(self.ds)
        idx = self.epoch_indices()
        stop = (n // self.batch_size) * self.batch_size if self.drop_last else n
        # The native row-gather operates on materialized arrays; for a lazy
        # (file-backed) dataset, fancy indexing IS the batch decode
        # (LazyImageArray thread pool), so use_native does not apply.
        if self.use_native and not getattr(self.ds, "is_lazy", False):
            from distributed_model_parallel_tpu.data import native
            for lo in range(0, stop, self.batch_size):
                sel = self._local_slice(idx[lo:lo + self.batch_size])
                yield (native.gather_rows(self.ds.images, sel,
                                          n_threads=self.num_workers),
                       self.ds.labels[sel])
        else:
            for lo in range(0, stop, self.batch_size):
                sel = self._local_slice(idx[lo:lo + self.batch_size])
                yield self.ds.images[sel], self.ds.labels[sel]


class PrefetchLoader:
    """Background-thread prefetch over any batch iterable — the capability of
    the reference's ``num_workers``/pinned-memory DataLoader settings
    (``data_parallel.py:44-51``) in single-controller form: batch k+1 is
    assembled on a host thread while the accelerator runs batch k."""

    def __init__(self, loader: Iterable, depth: int = 2):
        self.loader = loader
        self.depth = depth

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        sentinel = object()
        stop = threading.Event()
        err: list[BaseException] = []

        def put(item) -> bool:
            # Bounded-wait put so the worker can never be stranded if the
            # consumer abandons the loop mid-epoch (exception in the train
            # step, KeyboardInterrupt, ...).
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self.loader:
                    if not put(item):
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            t.join()
            if err:
                raise err[0]


def maybe_prefetch(loader: Iterable, depth: int) -> Iterable:
    """Wrap ``loader`` in a PrefetchLoader when ``depth > 0`` (else as-is)."""
    return PrefetchLoader(loader, depth=depth) if depth > 0 else loader


def resolve_input_size(images_shape, image_size: int) -> tuple[int | None, int]:
    """(resize_to, input_hw) for the on-device resize input stage.

    ``resize_to`` is None when the configured ``image_size`` already matches
    the dataset's native resolution (no resize step compiled in). Shared by
    the DP and pipeline trainers so the squareness assumption is validated
    in exactly one place (ADVICE r2: comparing height alone would silently
    skip the resize for a non-square dataset whose height matches).
    """
    native_h, native_w = images_shape[1:3]
    if native_h != native_w:
        raise ValueError(
            f"the resize/input path assumes square images; dataset is "
            f"{native_h}x{native_w} — pre-crop it square")
    resize_to = image_size if image_size != native_h else None
    return resize_to, (resize_to or native_h)


def resize_batch(images_u8: jnp.ndarray, size: int) -> jnp.ndarray:
    """On-device bilinear resize NHWC uint8 -> (B, size, size, C) uint8.

    The input stage the reference's 224px finetune recipe needs
    (``Readme.md:186-196``: CIFAR images upsampled to the pretrained
    backbone's native resolution). Runs on the accelerator inside the train
    step — the wire still carries the small native-size uint8 batch, and
    XLA fuses the upsample with augmentation/normalization.
    """
    b, h, w, c = images_u8.shape
    if (h, w) == (size, size):
        return images_u8
    x = jax.image.resize(images_u8.astype(jnp.float32), (b, size, size, c),
                         method="bilinear")
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)


def normalize(images_u8: jnp.ndarray, mean: np.ndarray, std: np.ndarray,
              dtype=jnp.float32) -> jnp.ndarray:
    """uint8 NHWC -> normalized float (on device)."""
    x = images_u8.astype(dtype) / jnp.asarray(255.0, dtype)
    return (x - jnp.asarray(mean, dtype)) / jnp.asarray(std, dtype)


def augment_batch(rng: jax.Array, images_u8: jnp.ndarray, *, pad: int = 4,
                  flip: bool = True) -> jnp.ndarray:
    """Random crop (pad-and-crop) + horizontal flip, vectorized on device.

    Equivalent to the reference's ``RandomCrop(32, padding=4)`` +
    ``RandomHorizontalFlip`` (``data_parallel.py:33-35``). The crop is two
    batched ``take_along_axis`` gathers (rows then columns) rather than a
    vmapped ``dynamic_slice`` — the per-image dynamic-slice form lowers to
    a pathological scatter/gather on TPU (~20x slower, measured on v5e).
    uint8 in, uint8 out.
    """
    b, h, w, c = images_u8.shape
    rng_crop, rng_flip = jax.random.split(rng)
    padded = jnp.pad(images_u8, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                     mode="constant")
    offs = jax.random.randint(rng_crop, (b, 2), 0, 2 * pad + 1)
    rows = offs[:, 0][:, None] + jnp.arange(h)[None, :]        # [B, H]
    cols = offs[:, 1][:, None] + jnp.arange(w)[None, :]        # [B, W]
    out = jnp.take_along_axis(padded, rows[:, :, None, None], axis=1)
    out = jnp.take_along_axis(out, cols[:, None, :, None], axis=2)
    if flip:
        do_flip = jax.random.bernoulli(rng_flip, 0.5, (b,))
        out = jnp.where(do_flip[:, None, None, None], out[:, :, ::-1, :], out)
    return out
