"""Dataset registry.

Capability parity with the reference's ``DatasetCollection`` factory keyed on a
string type — Imagenet / CUB200 / CIFAR10 / Place365
(``dataset/dataset_collection.py:28-69``) — behind one interface that returns
in-memory or lazily-decoded arrays in NHWC uint8. This environment has zero
egress, so every dataset falls back to a deterministic synthetic stand-in of
the right shape when the on-disk data is absent (``DataConfig.synthetic_ok``);
real data is read when present:

* ``cifar10``   — the standard ``cifar-10-batches-py`` pickle format.
* ``imagenet`` / ``place365`` — ImageFolder layout (``root/train/<cls>/*.jpg``,
  ``root/val/<cls>/*.jpg``), decoded with PIL (reference
  ``dataset_collection.py:36-47,66-69``).
* ``cub200``    — the CUB-200-2011 metadata files ``images.txt``,
  ``image_class_labels.txt``, ``train_test_split.txt`` joined on image id
  (reference ``dataset_collection.py:8-27,48-61``, which does the same join
  with pandas).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Callable

import numpy as np

# Reference normalization stats (data_parallel.py:31-40 uses the standard
# CIFAR-10 mean/std).
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class LazyImageArray:
    """Array-like view over on-disk images, decoded per access.

    Stores only file paths; ``lazy[idx_array]`` decodes exactly those
    images (PIL, thread pool) into an NHWC uint8 batch — so a dataset's
    host-memory footprint is its path list, not its pixels, and ImageNet-
    scale ImageFolders stream through ``BatchLoader`` batch by batch
    (reference parity: torchvision's ImageFolder is lazy the same way,
    ``dataset_collection.py:36-47``). Exposes the slice of the ndarray
    interface the loaders use (``shape``/``dtype``/``len``/fancy index);
    whole-array conversion is refused loudly — silently decoding N images
    because something called ``np.asarray`` is exactly the footgun this
    class exists to remove.
    """

    dtype = np.uint8

    def __init__(self, paths: list[str], image_size: int,
                 num_workers: int = 8):
        self.paths = list(paths)
        self.image_size = image_size
        self.num_workers = num_workers
        self._pool = None          # created on first batch, then reused

    @property
    def shape(self) -> tuple[int, int, int, int]:
        return (len(self.paths), self.image_size, self.image_size, 3)

    def __len__(self) -> int:
        return len(self.paths)

    def _decode(self, path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB").resize((self.image_size, self.image_size))
            return np.asarray(im, np.uint8)

    def __getitem__(self, idx) -> np.ndarray:
        if np.isscalar(idx) or isinstance(idx, (int, np.integer)):
            return self._decode(self.paths[int(idx)])
        idx = np.asarray(idx)
        out = np.empty((len(idx), *self.shape[1:]), np.uint8)
        if len(idx) == 0:
            return out

        def work(j):
            out[j] = self._decode(self.paths[int(idx[j])])

        if self.num_workers > 1 and len(idx) > 1:
            if self._pool is None:
                # One persistent pool per array, reused across batches —
                # this is the hot input path; a per-batch pool would pay
                # thread create/join once per step. close() / __del__
                # shuts it down (ADVICE r4: the eager decode-once path
                # would otherwise leak idle workers per split).
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(self.num_workers)
            list(self._pool.map(work, range(len(idx))))
        else:
            for j in range(len(idx)):
                work(j)
        return out

    def close(self) -> None:
        """Shut down the decode pool (idempotent)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        self.close()

    def __array__(self, *args, **kwargs):
        raise TypeError(
            f"refusing to materialize all {len(self)} lazily-decoded "
            f"images ({np.prod(self.shape) / 1e9:.1f} GB) into host "
            f"memory; stream batches via BatchLoader, or set "
            f"DataConfig.lazy_decode=False to decode eagerly")


@dataclasses.dataclass
class ArrayDataset:
    """A materialized (or lazily-decoded) labeled image set, NHWC uint8."""

    images: "np.ndarray | LazyImageArray"   # (N, H, W, C) uint8
    labels: np.ndarray                      # (N,) int32
    num_classes: int
    mean: np.ndarray = dataclasses.field(default_factory=lambda: CIFAR10_MEAN)
    std: np.ndarray = dataclasses.field(default_factory=lambda: CIFAR10_STD)

    def __len__(self) -> int:
        return len(self.labels)

    @property
    def is_lazy(self) -> bool:
        return isinstance(self.images, LazyImageArray)


def _synthetic(n: int, image_size: int, num_classes: int, seed: int,
               mean=CIFAR10_MEAN, std=CIFAR10_STD) -> ArrayDataset:
    """Deterministic class-conditional synthetic images (learnable signal, so
    smoke-training shows decreasing loss rather than pure noise)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    base = rng.integers(0, 256, size=(num_classes, image_size, image_size, 3))
    noise = rng.integers(-40, 41, size=(n, image_size, image_size, 3))
    images = np.clip(base[labels] + noise, 0, 255).astype(np.uint8)
    return ArrayDataset(images=images, labels=labels, num_classes=num_classes,
                        mean=mean, std=std)


def _load_cifar10(root: str) -> tuple[ArrayDataset, ArrayDataset] | None:
    d = os.path.join(root, "cifar-10-batches-py")
    if not os.path.isdir(d):
        return None

    def read(names):
        xs, ys = [], []
        for name in names:
            with open(os.path.join(d, name), "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(batch[b"data"], np.uint8))
            ys.append(np.asarray(batch[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return np.ascontiguousarray(x), np.concatenate(ys)

    xtr, ytr = read([f"data_batch_{i}" for i in range(1, 6)])
    xte, yte = read(["test_batch"])
    mk = lambda x, y: ArrayDataset(x, y, 10, CIFAR10_MEAN, CIFAR10_STD)
    return mk(xtr, ytr), mk(xte, yte)


# Auto threshold for lazy decode (DataConfig.lazy_decode=None): datasets
# whose decoded pixels exceed this stay on disk and stream per batch.
LAZY_AUTO_BYTES = 2 << 30


def _build_split(paths: list[str], labels: list[int], image_size: int,
                 num_classes: int, mean, std, lazy: bool | None,
                 num_workers: int) -> ArrayDataset:
    """Assemble one split as eager pixels or a LazyImageArray.

    ``lazy=None`` decides by decoded size (> LAZY_AUTO_BYTES streams) —
    small sets keep the decode-once speed, ImageNet-scale sets are no
    longer bounded by host RAM (VERDICT r3 weak #6)."""
    y = np.asarray(labels, np.int32)
    if lazy is None:
        lazy = len(paths) * image_size * image_size * 3 > LAZY_AUTO_BYTES
    imgs = LazyImageArray(paths, image_size, num_workers=num_workers)
    if not lazy:
        decoded = imgs[np.arange(len(paths))]  # decode once, keep pixels
        imgs.close()                           # don't leak the decode pool
        imgs = decoded
    return ArrayDataset(imgs, y, num_classes, mean, std)


def _load_imagefolder(root: str, image_size: int,
                      mean=IMAGENET_MEAN, std=IMAGENET_STD, *,
                      lazy: bool | None = None, num_workers: int = 8
                      ) -> tuple[ArrayDataset, ArrayDataset] | None:
    """ImageFolder layout: root/{train,val}/<class>/<img>. Collects paths
    and labels only; pixels decode eagerly or per batch (``_build_split``)."""
    tr, va = os.path.join(root, "train"), os.path.join(root, "val")
    if not (os.path.isdir(tr) and os.path.isdir(va)):
        return None

    def scan(split_dir, class_to_idx=None):
        classes = sorted(e.name for e in os.scandir(split_dir) if e.is_dir())
        if class_to_idx is None:
            class_to_idx = {c: i for i, c in enumerate(classes)}
        paths, ys = [], []
        for c in classes:
            cdir = os.path.join(split_dir, c)
            for e in sorted(os.scandir(cdir), key=lambda e: e.name):
                if e.is_file():
                    paths.append(e.path)
                    ys.append(class_to_idx[c])
        return paths, ys, class_to_idx

    ptr, ytr, c2i = scan(tr)
    pte, yte, _ = scan(va, c2i)
    n = len(c2i)
    return (_build_split(ptr, ytr, image_size, n, mean, std, lazy,
                         num_workers),
            _build_split(pte, yte, image_size, n, mean, std, lazy,
                         num_workers))


def _load_cub200(root: str, image_size: int, *,
                 lazy: bool | None = None, num_workers: int = 8
                 ) -> tuple[ArrayDataset, ArrayDataset] | None:
    """CUB-200-2011: join images.txt / image_class_labels.txt /
    train_test_split.txt on image id (reference dataset_collection.py:48-61).
    The join yields path lists; pixels decode per ``_build_split``."""
    meta = {n: os.path.join(root, n) for n in
            ("images.txt", "image_class_labels.txt", "train_test_split.txt")}
    if not all(os.path.isfile(p) for p in meta.values()):
        return None

    def read_table(path):
        out = {}
        with open(path) as f:
            for line in f:
                k, v = line.split()
                out[int(k)] = v
        return out

    paths = read_table(meta["images.txt"])
    labels = {k: int(v) - 1 for k, v in read_table(meta["image_class_labels.txt"]).items()}
    is_train = {k: v == "1" for k, v in read_table(meta["train_test_split.txt"]).items()}
    splits = {True: ([], []), False: ([], [])}
    for img_id, rel in sorted(paths.items()):
        ps, ys = splits[is_train[img_id]]
        ps.append(os.path.join(root, "images", rel))
        ys.append(labels[img_id])
    n = max(labels.values()) + 1
    mk = lambda ps, ys: _build_split(ps, ys, image_size, n, IMAGENET_MEAN,
                                     IMAGENET_STD, lazy, num_workers)
    return mk(*splits[True]), mk(*splits[False])


_LOADERS: dict[str, Callable] = {
    "cifar10": lambda cfg: _load_cifar10(cfg.root),
    "imagenet": lambda cfg: _load_imagefolder(
        os.path.join(cfg.root, "imagenet"), cfg.image_size,
        lazy=cfg.lazy_decode, num_workers=max(1, cfg.num_workers)),
    "place365": lambda cfg: _load_imagefolder(
        os.path.join(cfg.root, "place365"), cfg.image_size,
        lazy=cfg.lazy_decode, num_workers=max(1, cfg.num_workers)),
    "cub200": lambda cfg: _load_cub200(
        os.path.join(cfg.root, "CUB_200_2011"), cfg.image_size,
        lazy=cfg.lazy_decode, num_workers=max(1, cfg.num_workers)),
}
_NUM_CLASSES = {"cifar10": 10, "imagenet": 1000, "place365": 365, "cub200": 200}


def load_dataset(cfg) -> tuple[ArrayDataset, ArrayDataset]:
    """(train, eval) for ``cfg.name`` (a DataConfig); synthetic fallback."""
    if cfg.name == "synthetic":
        loaded = None
        num_classes = 10
    else:
        if cfg.name not in _LOADERS:
            raise KeyError(f"unknown dataset {cfg.name!r}; known: "
                           f"{sorted(_LOADERS)} + synthetic")
        loaded = _LOADERS[cfg.name](cfg)
        num_classes = _NUM_CLASSES[cfg.name]
    if loaded is not None:
        return loaded
    if not cfg.synthetic_ok and cfg.name != "synthetic":
        raise FileNotFoundError(
            f"dataset {cfg.name!r} not found under {cfg.root!r} and "
            f"synthetic_ok=False")
    native = cfg.synthetic_native_size or cfg.image_size
    return (_synthetic(cfg.synthetic_train_size, native, num_classes,
                       cfg.seed),
            _synthetic(cfg.synthetic_eval_size, native, num_classes,
                       cfg.seed + 1))
