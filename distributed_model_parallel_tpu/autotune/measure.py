"""Measured validation: time short real steps of the top-K candidates.

The analytic ranking is only as good as its coefficients, so the planner
can close the loop with measurements: ``scripts/dmp_plan.py --measure K``
builds each of the analytic top-K plans through **bench.py's shared
workload builders** (``build_lm_bench`` with a per-plan mesh override —
the measured program IS the bench program, so the numbers are comparable
with BENCH_* artifacts) and times a handful of dispatched steps with the
same fetch-bracketed discipline as ``utils/profiling.time_step`` (a host
fetch is the only trustworthy sync point on the remote-TPU tunnel — see
that module's docstring).

This module holds only the timing harness; the bench-builder plumbing
lives in ``scripts/dmp_plan.py`` (the repo-root ``bench`` module is a
script, not a package member).
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from distributed_model_parallel_tpu.autotune.plan import ParallelPlan

__all__ = ["measure_plans", "time_step_fn"]


def time_step_fn(step: Callable[[], object], *, warmup: int = 1,
                 iters: int = 2) -> float:
    """Seconds per call of ``step()`` (one train step): ``warmup`` calls
    (compile + warm), then ``iters`` back-to-back calls bracketed by ONE
    host fetch, minus the separately-measured fetch round trip."""
    from distributed_model_parallel_tpu.utils.profiling import (
        fetch,
        fetch_overhead,
    )

    out = None
    for _ in range(max(1, warmup)):
        out = step()
    fetch(out)
    t_fetch = fetch_overhead()
    t0 = time.perf_counter()
    for _ in range(max(1, iters)):
        out = step()
    fetch(out)
    return max(1e-9, time.perf_counter() - t0 - t_fetch) / max(1, iters)


def measure_plans(plans: Sequence[ParallelPlan],
                  build_step: Callable[[ParallelPlan], Callable[[], object]],
                  *, warmup: int = 1, iters: int = 2) -> list[dict]:
    """Measure each plan through ``build_step(plan) -> step()`` (a fresh
    per-plan program — mesh layout is compile-time). Returns one row per
    plan, measurement order preserved; a candidate whose build/compile
    fails records its error instead of killing the sweep (the analytic
    ranking still stands for it)."""
    rows: list[dict] = []
    for p in plans:
        row = dict(p.payload())
        try:
            row["measured_s"] = time_step_fn(build_step(p), warmup=warmup,
                                             iters=iters)
        except Exception as e:  # noqa: BLE001 - reported, not fatal
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows
