"""The planner: enumerate -> filter -> rank -> (optionally) measure ->
emit.

``plan_parallelism`` is the pure core: given a probed
:class:`~distributed_model_parallel_tpu.autotune.search.WorkloadSpec` and
a device count it enumerates every feasible ``(dp, pp, tp, sp, ep)``
layout (search.py), drops the ones the HBM filter rejects (memory.py),
ranks the survivors with the alpha-beta cost model (cost_model.py) and —
when the caller supplies a ``measure_fn`` — validates the analytic top-K
with short measured steps, letting a measurement overrule the model.
Everything is deterministic: same workload + device count + coefficients
-> the identical ranked list (ties break on the plan tuple, never hash
order).

Entry points the rest of the tree uses:

* ``plan_for_cnn`` / ``plan_for_lm`` / ``plan_for_stage_pipeline`` —
  ``strategy="auto"`` routing for the three trainers: probe the config's
  workload, plan on the LIVE device count, and return the rewritten
  config (an elastic restart therefore RE-PLANS on the refitted mesh
  instead of blindly shrinking dp — the planner may move devices to a
  different axis entirely);
* ``emit_plan_record`` — the typed ``plan`` telemetry record
  (docs/OBSERVABILITY.md) every auto run writes, stamped with the global
  step it planned at;
* ``scripts/dmp_plan.py`` — the CLI over the same core.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping, Sequence

from distributed_model_parallel_tpu.autotune import cost_model, memory, search
from distributed_model_parallel_tpu.autotune.cost_model import (
    CostCoefficients,
    PlanCost,
)
from distributed_model_parallel_tpu.autotune.plan import (
    ParallelPlan,
    mesh_from_plan,
)
from distributed_model_parallel_tpu.autotune.search import WorkloadSpec

__all__ = [
    "InfeasiblePlanError",
    "PlanDecision",
    "RankedPlan",
    "emit_plan_record",
    "lm_model_for_plan",
    "plan_for_cnn",
    "plan_for_lm",
    "plan_for_stage_pipeline",
    "plan_parallelism",
]


class InfeasiblePlanError(ValueError):
    """No candidate layout satisfies the constraints (device count,
    divisibility, memory). Carries the per-candidate rejection reasons so
    the fix is actionable, not archaeology."""


@dataclasses.dataclass(frozen=True)
class RankedPlan:
    plan: ParallelPlan
    cost: PlanCost
    memory: Mapping[str, float]

    def payload(self) -> dict:
        return {**self.plan.payload(), "cost": self.cost.payload(),
                "mem_bytes_per_device": self.memory.get("total")}


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    """The full outcome of one planning call — what was considered, what
    survived, what won, and why."""

    workload: str
    n_devices: int
    hbm_bytes: float | None
    ranked: tuple[RankedPlan, ...]          # feasible, best-first
    rejected: tuple[tuple[ParallelPlan, str], ...]
    chosen: RankedPlan
    measured: tuple[dict, ...] | None = None
    reason: str = "startup"                 # "startup" | "elastic-replan"

    @property
    def measurement_won(self) -> bool:
        """Whether a successful measurement actually picked ``chosen``
        (error-only measured rows keep the analytic best)."""
        return bool(self.measured) and any("measured_s" in m
                                           for m in self.measured)

    def describe(self) -> str:
        chosen = self.chosen
        how = "measured-best" if self.measurement_won else "analytic-best"
        return (f"autotune[{self.workload}]: {chosen.plan.describe()} "
                f"({how} of {len(self.ranked)} feasible / "
                f"{len(self.ranked) + len(self.rejected)} candidates "
                f"on {self.n_devices} devices, "
                f"predicted {chosen.cost.total_s * 1e3:.3g} ms/step)")

    def telemetry_payload(self, *, global_step: int = 0) -> dict:
        out = {
            "workload": self.workload,
            "reason": self.reason,
            "n_devices": self.n_devices,
            "global_step": int(global_step),
            "hbm_bytes": self.hbm_bytes,
            "n_feasible": len(self.ranked),
            "n_rejected": len(self.rejected),
            **self.chosen.payload(),
            "top": [r.payload() for r in self.ranked[:5]],
        }
        if self.measured is not None:
            out["measured"] = list(self.measured)
        return out


def _plan_sort_key(r: RankedPlan):
    return (r.cost.total_s, r.plan)


def plan_parallelism(workload: WorkloadSpec, n_devices: int, *,
                     coeffs: CostCoefficients | None = None,
                     hbm_bytes: float | None = None,
                     observed: Mapping[str, Mapping[str, float]] | None = None,
                     strategies: Sequence[str] | None = None,
                     candidates: Sequence[ParallelPlan] | None = None,
                     measure_fn: Callable[[ParallelPlan], float] | None = None,
                     measure_top: int = 0,
                     allow_undersubscribe: bool = False,
                     reason: str = "startup") -> PlanDecision:
    """Plan the mesh layout (module docstring).

    ``measure_fn(plan) -> seconds/step`` validates the analytic top
    ``measure_top`` candidates when provided; the measured-best becomes
    ``chosen`` (the analytic ranking is kept alongside). ``candidates``
    overrides enumeration for constrained spaces (the single-controller
    pipeline). ``allow_undersubscribe=True`` (the trainers' auto path)
    retries at the largest smaller device count when no factorization of
    ``n_devices`` is feasible — a 7-device slice after a quarantine
    plans 4/7 devices rather than crashing the restart, matching
    ``fit_mesh_to_devices``'s graceful shrink. Raises
    :class:`InfeasiblePlanError` when nothing survives.
    """
    coeffs = coeffs if coeffs is not None else \
        cost_model.default_coefficients()
    if candidates is None:
        candidates = search.enumerate_plans(workload, n_devices,
                                            strategies=strategies)
        n = n_devices
        while not candidates and allow_undersubscribe and n > 1:
            n -= 1
            candidates = search.enumerate_plans(workload, n,
                                                strategies=strategies)
        n_devices = n if candidates else n_devices
    if not candidates:
        raise InfeasiblePlanError(
            f"no {workload.kind} layout of {n_devices} devices satisfies "
            f"the divisibility constraints (batch={workload.batch_size}; "
            f"see autotune/search.py for the per-axis rules)")
    ranked: list[RankedPlan] = []
    rejected: list[tuple[ParallelPlan, str]] = []
    for p in candidates:
        fits, est, why = memory.memory_feasible(workload, p, hbm_bytes)
        if not fits:
            rejected.append((p, why or "memory"))
            continue
        ranked.append(RankedPlan(
            plan=p, cost=cost_model.plan_cost(workload, p, coeffs,
                                              observed=observed),
            memory=est))
    if not ranked:
        detail = "; ".join(f"{p.describe()}: {why}"
                           for p, why in rejected[:8])
        raise InfeasiblePlanError(
            f"all {len(rejected)} candidate layouts rejected by the "
            f"HBM feasibility filter — {detail}")
    ranked.sort(key=_plan_sort_key)

    measured: tuple[dict, ...] | None = None
    chosen = ranked[0]
    if measure_fn is not None and measure_top > 0:
        rows = []
        for r in ranked[:measure_top]:
            row = {**r.plan.payload(), "predicted_s": r.cost.total_s}
            try:
                row["measured_s"] = float(measure_fn(r.plan))
            except Exception as e:  # noqa: BLE001 - one candidate's
                # build/compile failure must not kill the sweep; the
                # analytic ranking still stands for it.
                row["error"] = f"{type(e).__name__}: {e}"
            rows.append(row)
        measured = tuple(rows)
        timed = [m for m in rows if "measured_s" in m]
        if timed:
            best = min(timed,
                       key=lambda m: (m["measured_s"], m["predicted_s"]))
            chosen = next(r for r in ranked
                          if r.plan.payload() == {k: best[k] for k in
                                                  ("strategy", "axes",
                                                   "num_microbatches")})
        # else: every candidate failed to measure — keep the analytic
        # best; the rows carry the errors for the caller to surface.
    return PlanDecision(
        workload=workload.kind, n_devices=n_devices, hbm_bytes=hbm_bytes,
        ranked=tuple(ranked), rejected=tuple(rejected), chosen=chosen,
        measured=measured, reason=reason)


def emit_plan_record(telemetry, decision: PlanDecision, *,
                     global_step: int = 0) -> None:
    """Write the typed ``plan`` record (docs/OBSERVABILITY.md) onto a
    TelemetryRun stream — stamped with the global step the run plans at,
    so an elastic re-plan is auditable at its exact resume point."""
    telemetry.record("plan",
                     **decision.telemetry_payload(global_step=global_step))


# ---------------------------------------------------------------------------
# strategy="auto" routing for the trainers
# ---------------------------------------------------------------------------

def _reason_for(config) -> str:
    """"elastic-replan" only for a restart that will actually resume:
    elastic + resume + something under the checkpoint directory (a fresh
    first start of an elastic-and-resumable config is still "startup" —
    the trainers' own resume gate checks slot existence the same way)."""
    import os

    if not (getattr(config, "elastic", False)
            and getattr(config, "resume", False)):
        return "startup"
    ckpt_dir = getattr(config, "checkpoint_dir", None)
    try:
        has_ckpt = bool(ckpt_dir) and bool(os.listdir(ckpt_dir))
    except OSError:
        has_ckpt = False
    return "elastic-replan" if has_ckpt else "startup"


def plan_for_cnn(config, n_devices: int):
    """Resolve ``TrainConfig(strategy="auto")``: probe the model, plan,
    and return ``(rewritten_config, PlanDecision)``.

    Per-strategy constraint pruning mirrors the trainers' own loud
    rejections, so the planner never picks a layout the Trainer would
    refuse: the consistency sentinel and fused optimizer exclude FSDP,
    EMA needs gspmd/fsdp, device-resident data needs gspmd/fsdp, and an
    explicit ``grad_bucket_mb`` pins the explicit DDP path.
    """
    if config.grad_bucket_mb is not None:
        strategies: tuple[str, ...] = ("ddp",)
    else:
        strategies = ("gspmd", "fsdp", "spmd_pipeline")
        if config.consistency_every or config.optimizer.fused:
            strategies = tuple(s for s in strategies if s != "fsdp")
        if (config.optimizer.ema_decay is not None
                or config.device_resident_data):
            strategies = tuple(s for s in strategies
                               if s in ("gspmd", "fsdp"))
    workload = search.cnn_workload(config.model, config.data)
    decision = plan_parallelism(
        workload, n_devices, hbm_bytes=memory.device_hbm_bytes(),
        strategies=strategies, allow_undersubscribe=True,
        reason=_reason_for(config))
    p = decision.chosen.plan
    new = config.replace(
        strategy=p.strategy, mesh=mesh_from_plan(p, config.mesh),
        num_microbatches=p.num_microbatches,
        # Pipeline plans balance their stage cut with the same unit costs
        # the workload probe measured (auto_partition.unit_costs).
        auto_partition=(config.auto_partition or p.pp > 1))
    return new, decision


def lm_model_for_plan(model, plan: ParallelPlan):
    """The model config a plan needs: tensor/sequence/expert parallelism
    live as model-config axis names (``tp_axis``/``sp_axis``/``ep_axis``
    — the same wiring scripts/train_lm.py does from its CLI degrees), so
    a planned degree > 1 must switch the matching axis on, and a degree
    of 1 must switch it off."""
    updates = {}
    for field, axis, degree in (("tp_axis", "model", plan.tp),
                                ("sp_axis", "seq", plan.sp),
                                ("ep_axis", "expert", plan.ep)):
        want = axis if degree > 1 else None
        if getattr(model, field) != want:
            updates[field] = want
    return dataclasses.replace(model, **updates) if updates else model


def plan_for_lm(config, n_devices: int):
    """Resolve ``LMTrainConfig(strategy="auto")``: plan the
    dp x pp x tp x sp x ep degrees of the single-jit SPMD program and
    return ``(rewritten_config, PlanDecision)``. Planned tensor /
    sequence / expert axes are switched on in the model config
    (:func:`lm_model_for_plan`)."""
    workload = search.lm_workload(config.model, config.batch_size,
                                  config.seq_len)
    decision = plan_parallelism(
        workload, n_devices, hbm_bytes=memory.device_hbm_bytes(),
        allow_undersubscribe=True, reason=_reason_for(config))
    p = decision.chosen.plan
    new = dataclasses.replace(
        config, strategy="spmd", model=lm_model_for_plan(config.model, p),
        mesh=mesh_from_plan(p, config.mesh),
        num_microbatches=p.num_microbatches)
    return new, decision


def plan_for_stage_pipeline(config, n_stages: int):
    """Resolve ``strategy="auto"`` for the single-controller
    PipelineTrainer: the stage count is fixed by the device list, so the
    planner picks the microbatch count (bubble vs boundary-latency) and
    turns the cost-balanced stage cut on. Returns
    ``(rewritten_config, PlanDecision)``."""
    workload = search.cnn_workload(config.model, config.data)
    decision = plan_parallelism(
        workload, n_stages, hbm_bytes=memory.device_hbm_bytes(),
        candidates=search.enumerate_stage_pipeline_plans(workload,
                                                         n_stages),
        reason=_reason_for(config))
    p = decision.chosen.plan
    new = config.replace(
        mesh=dataclasses.replace(config.mesh, stage=n_stages),
        num_microbatches=p.num_microbatches,
        auto_partition=config.auto_partition
        or config.stage_boundaries is None)
    return new, decision
