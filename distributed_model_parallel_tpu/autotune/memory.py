"""HBM memory-feasibility filter: reject layouts that cannot fit.

Per-device footprint of a candidate plan, modeled after how THIS repo
actually lays state out (not an idealized sharding):

* **params** — LM: blocks shard over (pp, tp), experts additionally over
  ep, replicated over dp (``parallel/spmd_pipeline.shard_params``).
  CNN: replicated, except FSDP (sharded over dp,
  ``parallel/fsdp.tree_shardings``) and the single-controller pipeline
  (each stage's params live on their own device, ~1/pp,
  ``parallel/pipeline.py``); the SPMD CNN pipeline replicates.
* **grads** — transient copy of the locally-owned params (same sharding;
  FSDP's reduce-scatter output is 1/dp).
* **optimizer state** — one f32 momentum copy (``train/optim``'s SGD).
  The LM trainer keeps opt_state REPLICATED (lm_trainer.py device_puts it
  with ``P()``), so pp/tp do not shrink it there — the model reflects
  that honestly rather than flattering pipeline plans. CNN: replicated,
  except FSDP (sharded over dp).
* **activations** — live residuals of the local layer/unit slice at the
  local batch (GPipe holds all M microbatches' residuals at peak, 1F1B
  bounds in-flight microbatches by the stage count), plus one
  microbatch's logits for the LM head (the largest single tensor at
  small models).

The capacity side comes from the live backend where it reports one
(``memory_stats()['bytes_limit']``), the per-device-kind table below
otherwise, or the caller's override (CPU test meshes, what-if planning).
"""

from __future__ import annotations

from typing import Mapping

from distributed_model_parallel_tpu.autotune.plan import ParallelPlan
from distributed_model_parallel_tpu.autotune.search import WorkloadSpec

__all__ = [
    "device_hbm_bytes",
    "estimate_plan_memory",
    "memory_feasible",
]

# Per-device HBM, bytes, by device_kind prefix (same longest-prefix keying
# as utils/profiling.TPU_PEAK_FLOPS). Published per-chip capacities.
TPU_HBM_CAPACITY_BYTES: dict[str, float] = {
    "TPU v6": 32e9,          # v6e (Trillium)
    "TPU v5p": 95e9,
    "TPU v5 lite": 16e9,     # v5e
    "TPU v5e": 16e9,
    "TPU v5": 95e9,
    "TPU v4": 32e9,
    "TPU v3": 16e9,
    "TPU v2": 8e9,
}

# Fraction of HBM a plan may claim: the rest covers XLA scratch,
# fragmentation, and the input pipeline's resident batches.
DEFAULT_FIT_FRACTION = 0.9


def device_hbm_bytes(default: float | None = None) -> float | None:
    """Per-device HBM capacity: backend-reported ``bytes_limit`` when
    available, the device-kind table otherwise, else ``default`` (None =
    unknown; the filter then passes everything and says so)."""
    try:
        import jax

        from distributed_model_parallel_tpu.utils.profiling import (
            match_device_kind,
        )

        d = jax.devices()[0]
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            return float(stats["bytes_limit"])
        cap = match_device_kind(TPU_HBM_CAPACITY_BYTES, d)
        if cap is not None:
            return float(cap)
    except Exception:
        pass
    return default


def estimate_plan_memory(w: WorkloadSpec, plan: ParallelPlan
                         ) -> dict[str, float]:
    """Per-device footprint breakdown (bytes) of one plan: params, grads,
    optimizer state, activations, and their ``total``."""
    dp, pp, tp, sp, ep = plan.dp, plan.pp, plan.tp, plan.sp, plan.ep
    M = max(1, plan.num_microbatches)
    local_b = max(1, w.batch_size // dp)
    micro_b = max(1, local_b // M)

    if w.kind == "lm":
        params = w.param_bytes / (pp * tp)
        if ep > 1 and w.expert_param_count:
            # Expert banks at the model's real storage width (bf16 params
            # are 2 B/param, not 4), sharded pp*tp like the rest.
            bytes_per_param = w.param_bytes / max(1, w.param_count)
            expert_bytes = (w.expert_param_count * bytes_per_param
                            / (pp * tp))
            params -= expert_bytes * (1 - 1 / ep)
        grads = params
        # Momentum is replicated in the LM trainer (module docstring).
        opt = w.param_count * 4.0
        seq_local = max(1, w.seq_len // sp)
        layers_local = max(1, w.n_layers // pp)
        # Residuals per microbatch per layer: ~2 block-IO copies under
        # remat; GPipe keeps all M microbatches' residuals live.
        inflight = M if pp > 1 else 1
        acts = (inflight * micro_b * seq_local * w.d_model
                * layers_local * 2 * w.dtype_bytes)
        # One microbatch's logits at the LM head.
        acts += micro_b * seq_local * w.vocab_size * w.dtype_bytes
    elif w.kind == "cnn":
        # FSDP shards over dp; the single-controller pipeline ("pipeline",
        # parallel/pipeline.py) places each stage's params + optimizer on
        # its own device (~1/pp each); the SPMD CNN pipeline and the
        # gspmd/ddp engines replicate (spmd_cnn_pipeline.py docstring).
        if plan.strategy == "fsdp":
            shard = dp
        elif plan.strategy == "pipeline":
            shard = max(1, pp)
        else:
            shard = 1
        params = w.param_bytes / shard
        grads = params
        opt = w.param_count * 4.0 / shard
        units_local = max(1, (w.n_units or 1) // max(1, pp))
        inflight = M if pp > 1 else 1
        acts = (inflight * micro_b * w.boundary_act_bytes_per_sample
                * units_local * 2)
    else:
        raise KeyError(f"unknown workload kind {w.kind!r}")
    out = {"params_bytes": float(params), "grads_bytes": float(grads),
           "opt_bytes": float(opt), "act_bytes": float(acts)}
    out["total"] = sum(out.values())
    return out


def memory_feasible(w: WorkloadSpec, plan: ParallelPlan,
                    hbm_bytes: float | None, *,
                    fit_fraction: float = DEFAULT_FIT_FRACTION
                    ) -> tuple[bool, Mapping[str, float], str | None]:
    """``(fits, breakdown, reason)``: whether the plan's estimated
    footprint fits ``fit_fraction`` of the per-device capacity. Unknown
    capacity (None) passes everything — the planner records that the
    filter did not run rather than silently trusting a made-up number."""
    est = estimate_plan_memory(w, plan)
    if hbm_bytes is None:
        return True, est, None
    budget = fit_fraction * hbm_bytes
    if est["total"] > budget:
        return False, est, (
            f"needs {est['total'] / 1e9:.2f} GB/device "
            f"> {budget / 1e9:.2f} GB budget "
            f"({fit_fraction:.0%} of {hbm_bytes / 1e9:.1f} GB)")
    return True, est, None
