"""Search space: workload probes + feasible-layout enumeration.

The planner never guesses model properties — it probes them once into a
:class:`WorkloadSpec` (param/FLOP/activation numbers) and then enumerates
every ``(dp, pp, tp, sp, ep)`` factorization of the device count that the
workload's divisibility rules allow:

* ``dp`` must divide the global batch (static shapes — ``mesh.
  local_batch_slice`` rejects uneven splits);
* ``pp`` must divide the LM block count (``parallel/spmd_pipeline`` splits
  the stacked blocks evenly) or stay within the staged CNN's unit count;
* ``tp`` must divide heads AND d_ff (Megatron column/row splits);
* ``sp`` must divide the sequence length AND the head count (ring shards
  the sequence, Ulysses additionally scatters heads);
* ``ep`` needs a routed MoE and must divide the expert count.

FLOP probes reuse the public ``parallel/auto_partition`` contract
(``unit_costs`` — XLA's compiled cost model per unit — for staged CNNs,
``utils/profiling.lm_model_flops`` analytically for the Transformer), so
the cost model ranks with the same numbers the pipeline balancer cuts by.

Enumeration is deterministic: candidates come out in sorted
``(strategy, dp, pp, tp, sp, ep)`` order, so equal inputs always produce
the identical candidate list (tests pin this).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterator, Sequence

__all__ = [
    "WorkloadSpec",
    "cnn_workload",
    "enumerate_plans",
    "enumerate_stage_pipeline_plans",
    "lm_workload",
    "pick_microbatches",
]

from distributed_model_parallel_tpu.autotune.plan import ParallelPlan

# Largest microbatch count the picker will choose: past this the GPipe
# bubble (S-1)/(M+S-1) is already small and each extra microbatch only
# adds boundary-ppermute latency (the alpha term).
MAX_MICROBATCHES = 32


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Everything the cost/memory models need to score a layout, probed
    once per planning call (no live model objects cross this boundary —
    the spec is plain data, picklable and hand-constructible in tests)."""

    kind: str                     # "lm" | "cnn"
    batch_size: int
    flops_per_step: float         # model FLOPs of ONE global-batch step
    param_count: int
    param_bytes: int              # at storage dtype (f32 here)
    dtype_bytes: int = 4          # activation/compute dtype width
    # -- LM geometry ---------------------------------------------------------
    seq_len: int = 0
    d_model: int = 0
    n_layers: int = 0
    n_heads: int = 0
    d_ff: int = 0
    vocab_size: int = 0
    moe_experts: int = 0
    moe_top_k: int = 1
    expert_param_count: int = 0   # subset of param_count sharded by ep
    # Sliding-window attention is incompatible with sequence parallelism
    # (models/transformer._attention rejects the combination), so a set
    # window pins sp = 1.
    attn_window: int | None = None
    # -- staged-CNN geometry -------------------------------------------------
    n_units: int = 0
    unit_flop_costs: tuple[float, ...] = ()
    # Largest inter-unit activation, bytes per sample (the pipeline
    # boundary payload).
    boundary_act_bytes_per_sample: int = 0


def _param_count(tree) -> int:
    import jax

    return int(sum(l.size for l in jax.tree.leaves(tree)))


def lm_workload(model_cfg, batch_size: int, seq_len: int) -> WorkloadSpec:
    """Probe a ``TransformerConfig`` into a WorkloadSpec.

    Parameter counts come from ``jax.eval_shape`` over the real
    ``init_params`` (exact, no compute); FLOPs from the analytic
    ``utils/profiling.lm_model_flops`` (XLA cost analysis cannot count the
    scanned/pallas LM program — see that docstring).
    """
    import jax
    import numpy as np

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.utils.profiling import lm_model_flops

    shapes = jax.eval_shape(
        functools.partial(tfm.init_params, cfg=model_cfg),
        jax.random.key(0))
    param_count = _param_count(shapes)
    param_bytes = int(sum(
        l.size * np.dtype(l.dtype).itemsize for l in jax.tree.leaves(shapes)))
    cfg = model_cfg
    expert_params = 0
    if cfg.moe_experts:
        # Per layer: expert FFN banks [E, d, f] + [E, f, d] (+ router d*E).
        expert_params = cfg.n_layers * cfg.moe_experts * (
            2 * cfg.d_model * cfg.d_ff + cfg.d_model)
    return WorkloadSpec(
        kind="lm", batch_size=batch_size,
        flops_per_step=lm_model_flops(cfg, batch_size, seq_len),
        param_count=param_count, param_bytes=param_bytes,
        dtype_bytes=np.dtype(cfg.dtype).itemsize,
        seq_len=seq_len, d_model=cfg.d_model, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, d_ff=cfg.d_ff, vocab_size=cfg.vocab_size,
        moe_experts=cfg.moe_experts, moe_top_k=cfg.moe_top_k,
        expert_param_count=min(expert_params, param_count),
        attn_window=cfg.attn_window)


def cnn_workload(model_cfg, data_cfg, *, probe_rows: int = 4) -> WorkloadSpec:
    """Probe a staged CNN (``models/get_model``) into a WorkloadSpec.

    Per-unit FLOPs come from the public ``parallel/auto_partition.
    unit_costs`` contract (XLA compiled cost analysis per unit, parameter
    proxy fallback) at ``probe_rows`` batch rows, scaled to the global
    batch; the forward count is tripled for fwd+bwd. A second
    ``eval_shape``-only walk of the unit chain records the largest
    inter-unit activation — the pipeline's boundary-hop payload.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.models import get_model
    from distributed_model_parallel_tpu.parallel.auto_partition import (
        unit_costs,
    )

    model = get_model(model_cfg)
    hw = data_cfg.image_size
    sample_shape = (probe_rows, hw, hw, 3)
    costs = unit_costs(model, sample_shape)

    x = jnp.zeros(sample_shape, jnp.float32)
    params, state = model.init(jax.random.key(0), x)
    boundary = 0
    for i in range(model.num_units):
        out = jax.eval_shape(
            lambda p, s, a, _i=i: model.apply_unit(_i, p, s, a, train=True)[0],
            params[i], state[i], x)
        if i < model.num_units - 1:   # the head's output never hops stages
            boundary = max(boundary, int(
                out.size // probe_rows * np.dtype(out.dtype).itemsize))
        x = jnp.zeros(out.shape, out.dtype)

    param_count = _param_count(params)
    scale = data_cfg.batch_size / probe_rows
    return WorkloadSpec(
        kind="cnn", batch_size=data_cfg.batch_size,
        flops_per_step=3.0 * float(sum(costs)) * scale,
        param_count=param_count, param_bytes=param_count * 4,
        dtype_bytes=4,
        n_units=model.num_units, unit_flop_costs=tuple(costs),
        boundary_act_bytes_per_sample=boundary)


def pick_microbatches(local_batch: int, pp: int,
                      cap: int = MAX_MICROBATCHES) -> int:
    """Microbatch count for a pp-deep pipeline at per-replica batch
    ``local_batch``: the largest divisor of the local batch within
    ``cap`` (more microbatches = smaller GPipe bubble; the cap bounds the
    per-microbatch boundary-latency alpha cost). pp==1 pipelines don't
    microbatch."""
    if pp <= 1 or local_batch <= 1:
        return 1
    return max(m for m in range(1, min(local_batch, cap) + 1)
               if local_batch % m == 0)


def _factorizations(n: int, k: int) -> Iterator[tuple[int, ...]]:
    """All ordered k-tuples of positive ints with product n, sorted."""
    if k == 1:
        yield (n,)
        return
    for d in sorted(d for d in range(1, n + 1) if n % d == 0):
        for rest in _factorizations(n // d, k - 1):
            yield (d,) + rest


def _lm_axes_feasible(w: WorkloadSpec, dp: int, pp: int, tp: int,
                      sp: int, ep: int) -> bool:
    if w.batch_size % dp:
        return False
    if pp > 1 and (w.n_layers == 0 or w.n_layers % pp):
        return False
    if tp > 1 and (w.n_heads % tp or w.d_ff % tp):
        return False
    # sp divides the sequence AND the LOCAL head count after the tp cut
    # (Ulysses scatters the heads tp left on each device; checking
    # heads % sp alone admits tp x sp combos that die at trace time),
    # and windowed attention pins sp = 1 (transformer._attention).
    if sp > 1 and (w.seq_len % sp
                   or (w.n_heads // max(1, tp)) % sp
                   or w.attn_window is not None):
        return False
    if ep > 1 and (not w.moe_experts or w.moe_experts % ep):
        return False
    return True


def enumerate_plans(workload: WorkloadSpec, n_devices: int, *,
                    strategies: Sequence[str] | None = None
                    ) -> list[ParallelPlan]:
    """Every feasible layout of ``n_devices`` for the workload, in
    deterministic sorted order (same inputs -> identical list).

    LM: one strategy ("spmd", the single-jit dp x pp x tp x sp x ep
    program) over all axis factorizations. CNN: the data-axis engines
    (gspmd / fsdp / optionally ddp) use every device as dp; the SPMD CNN
    pipeline contributes every (dp, pp>=2) split within the unit count.
    """
    if n_devices < 1:
        raise ValueError(f"need >= 1 device, got {n_devices}")
    out: list[ParallelPlan] = []
    if workload.kind == "lm":
        for dp, pp, tp, sp, ep in _factorizations(n_devices, 5):
            if not _lm_axes_feasible(workload, dp, pp, tp, sp, ep):
                continue
            m = pick_microbatches(workload.batch_size // dp, pp)
            out.append(ParallelPlan("spmd", dp, pp, tp, sp, ep,
                                    num_microbatches=m))
    elif workload.kind == "cnn":
        strategies = tuple(strategies if strategies is not None
                           else ("gspmd", "fsdp", "spmd_pipeline"))
        for s in strategies:
            if s in ("gspmd", "ddp", "fsdp"):
                if workload.batch_size % n_devices == 0:
                    out.append(ParallelPlan(s, dp=n_devices))
            elif s == "spmd_pipeline":
                for pp in sorted(d for d in range(2, n_devices + 1)
                                 if n_devices % d == 0):
                    dp = n_devices // pp
                    if workload.n_units and pp > workload.n_units:
                        continue
                    if workload.batch_size % dp:
                        continue
                    m = pick_microbatches(workload.batch_size // dp, pp)
                    out.append(ParallelPlan(s, dp=dp, pp=pp,
                                            num_microbatches=m))
            else:
                raise KeyError(f"unknown cnn strategy {s!r}")
    else:
        raise KeyError(f"unknown workload kind {workload.kind!r}")
    return sorted(out)


def enumerate_stage_pipeline_plans(workload: WorkloadSpec, n_stages: int
                                   ) -> list[ParallelPlan]:
    """Single-controller PipelineRunner space (train/pipeline_trainer.py):
    the stage count is fixed by the device list, so the only free knob is
    the microbatch count — one candidate per divisor of the batch."""
    if workload.batch_size < 1:
        raise ValueError("batch_size must be >= 1")
    return sorted(
        ParallelPlan("pipeline", dp=1, pp=n_stages, num_microbatches=m)
        for m in range(1, min(workload.batch_size, MAX_MICROBATCHES) + 1)
        if workload.batch_size % m == 0)
