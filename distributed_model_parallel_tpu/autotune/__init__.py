"""Cost-model-driven parallelism autotuner (docs/AUTOTUNE.md).

Given a model config and the live mesh, the planner (1) enumerates every
feasible ``(dp, pp, tp, sp, ep)`` factorization of the device count
(search.py — pruned by batch divisibility, per-strategy constraints and
the HBM feasibility filter in memory.py), (2) ranks them with an
alpha-beta comm/compute cost model whose comm terms are the SAME
ring-model estimators ``ops/collectives.py`` accounts into telemetry at
trace time and whose compute term reuses the public
``parallel/auto_partition`` compiled-FLOPs contract (cost_model.py),
(3) optionally validates the analytic top-K with short measured steps
through bench.py's shared workload builders (measure.py +
scripts/dmp_plan.py), and (4) emits the chosen layout as a typed ``plan``
telemetry record (planner.py).

Entry points: ``strategy="auto"`` on the three trainers routes through
``plan_for_cnn`` / ``plan_for_lm`` / ``plan_for_stage_pipeline`` —
elastic restarts re-plan on the refitted mesh instead of blindly
shrinking dp — and ``scripts/dmp_plan.py`` exposes the planner as a CLI.
"""

from distributed_model_parallel_tpu.autotune.cost_model import (  # noqa: F401
    Collective,
    CostCoefficients,
    PlanCost,
    collective_time_s,
    default_coefficients,
    observed_comm_table,
    plan_collectives,
    plan_cost,
)
from distributed_model_parallel_tpu.autotune.measure import (  # noqa: F401
    measure_plans,
    time_step_fn,
)
from distributed_model_parallel_tpu.autotune.memory import (  # noqa: F401
    device_hbm_bytes,
    estimate_plan_memory,
    memory_feasible,
)
from distributed_model_parallel_tpu.autotune.plan import (  # noqa: F401
    ParallelPlan,
    mesh_from_plan,
    plan_payload,
)
from distributed_model_parallel_tpu.autotune.planner import (  # noqa: F401
    InfeasiblePlanError,
    PlanDecision,
    RankedPlan,
    emit_plan_record,
    lm_model_for_plan,
    plan_for_cnn,
    plan_for_lm,
    plan_for_stage_pipeline,
    plan_parallelism,
)
from distributed_model_parallel_tpu.autotune.search import (  # noqa: F401
    WorkloadSpec,
    cnn_workload,
    enumerate_plans,
    enumerate_stage_pipeline_plans,
    lm_workload,
    pick_microbatches,
)
