"""Alpha-beta comm/compute cost model for candidate layouts.

One collective over an n-way axis costs

    count * ( alpha * ops(kind, n)  +  wire_bytes(kind, payload, n) / BW )

where ``ops``/``wire_bytes`` are the SAME ring-algorithm estimators the
``ops/collectives.py`` wrappers account into the telemetry registry at
trace time (``utils/telemetry.wire_ops_estimate`` /
``wire_bytes_estimate``) — the planner's analytic schedule and the
trace-time comm table are one accounting, so measured runs can audit the
prediction. ``alpha`` is per-message launch/latency, ``BW`` the per-device
wire bandwidth (ring model: every device sends/receives its share).

The compute term divides the workload's model FLOPs (probed via the
``parallel/auto_partition`` compiled-FLOPs contract or the analytic LM
count — autotune/search.py) over the FLOP-partitioning axes and the chip
peak from ``utils/profiling.TPU_PEAK_FLOPS``; pipeline plans multiply by
the GPipe bubble ``(M + S - 1) / M`` (steady-state throughput is set by
the bubble-inflated critical path).

Seeding from live runs: :func:`observed_comm_table` parses the per-axis
byte/op totals that ``ops/collectives.py`` accounted at trace time out of
a registry (or a telemetry ``metrics`` record) and :func:`plan_cost`
substitutes them for the analytic volumes on matching axes — a plan
re-ranked after one traced step uses observed, not modeled, comm volume.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from distributed_model_parallel_tpu.autotune.plan import ParallelPlan
from distributed_model_parallel_tpu.autotune.search import WorkloadSpec
from distributed_model_parallel_tpu.utils.telemetry import (
    wire_bytes_estimate,
    wire_ops_estimate,
)

__all__ = [
    "Collective",
    "CostCoefficients",
    "PlanCost",
    "bubble_factor",
    "collective_time_s",
    "default_coefficients",
    "observed_comm_table",
    "plan_collectives",
    "plan_cost",
]

# Ranking fallbacks for platforms without a profiling-table entry (CPU
# test meshes): the absolute seconds are meaningless there, but every
# candidate is scored against the SAME constants, so the ranking — the
# only thing the planner consumes — stays meaningful and deterministic.
FALLBACK_PEAK_FLOPS = 197e12      # v5e-class chip
FALLBACK_WIRE_BYTES_PER_S = 9e10  # per-device ICI ring share
DEFAULT_ALPHA_S = 1e-6            # per collective message (launch+latency)


@dataclasses.dataclass(frozen=True)
class CostCoefficients:
    """Alpha-beta-gamma coefficients: s/message, wire bytes/s, FLOP/s.

    ``overlap_fraction`` is the share of the compute time that
    OVERLAPPABLE collectives (the data-axis gradient reduction, which XLA
    schedules against the backward — the comm-hidden fraction
    ``dmp_report.py`` measures from xplane traces) can hide under; 0
    prices every byte on the critical path.
    """

    alpha_s: float = DEFAULT_ALPHA_S
    wire_bytes_per_s: float = FALLBACK_WIRE_BYTES_PER_S
    peak_flops_per_s: float = FALLBACK_PEAK_FLOPS
    overlap_fraction: float = 0.5


def default_coefficients(device=None) -> CostCoefficients:
    """Coefficients for the live backend: chip peak from the profiling
    tables where known, the documented fallbacks otherwise."""
    from distributed_model_parallel_tpu.utils.profiling import (
        peak_flops_per_chip,
    )

    try:
        peak = peak_flops_per_chip(device)
    except Exception:
        peak = None
    return CostCoefficients(peak_flops_per_s=peak or FALLBACK_PEAK_FLOPS)


@dataclasses.dataclass(frozen=True)
class Collective:
    """``count`` executions per step of one collective: ``kind`` over an
    n-way ``axis`` moving ``payload_bytes`` logical payload each.
    ``overlappable`` marks gradient reductions the backward can hide
    (CostCoefficients.overlap_fraction); activation collectives sit on
    the critical path and never are."""

    kind: str
    axis: str
    payload_bytes: float
    n: int
    count: float = 1.0
    overlappable: bool = False


def collective_time_s(c: Collective, coeffs: CostCoefficients) -> float:
    """Alpha-beta time of ``count`` executions (module docstring)."""
    return c.count * (
        coeffs.alpha_s * wire_ops_estimate(c.kind, c.n)
        + wire_bytes_estimate(c.kind, c.payload_bytes, c.n)
        / coeffs.wire_bytes_per_s)


def plan_collectives(w: WorkloadSpec, plan: ParallelPlan
                     ) -> list[Collective]:
    """The analytic per-step collective schedule of a plan.

    Per-axis terms (all payloads are logical, the estimators apply the
    ring factors):

    * ``data``  — gradient allreduce of the locally-owned parameter shard
      (gspmd/ddp/spmd/spmd_pipeline); FSDP instead all-gathers params
      twice (fwd + bwd re-gather) and reduce-scatters gradients;
    * ``stage`` — one boundary ppermute per pipeline tick, 2(M+S-1) total
      (fwd + bwd sweeps), microbatch-activation payload;
    * ``model`` — Megatron's 4 activation allreduces per owned layer per
      microbatch;
    * ``seq``   — 4 all-to-alls per owned layer per microbatch
      (Ulysses-style head/sequence exchange; ring attention's ppermute
      chain moves the same K/V volume);
    * ``expert``— dispatch+combine all-to-alls, top_k-scaled token
      payload.
    """
    out: list[Collective] = []
    dp, pp, tp, sp, ep = plan.dp, plan.pp, plan.tp, plan.sp, plan.ep
    M = max(1, plan.num_microbatches)
    local_b = max(1, w.batch_size // dp)
    micro_b = max(1, local_b // M)

    if w.kind == "lm":
        seq_local = max(1, w.seq_len // sp)
        micro_act = micro_b * seq_local * w.d_model * w.dtype_bytes
        layers_local = max(1, w.n_layers // pp)
        # Parameters this device owns (grad-sync payload): blocks shard
        # over pp and tp, experts additionally over ep.
        param_local_bytes = w.param_bytes / (pp * tp)
        if ep > 1 and w.expert_param_count:
            # Expert banks at the model's real storage width, like the
            # memory model (memory.py) — not a hardcoded 4 B/param.
            bytes_per_param = w.param_bytes / max(1, w.param_count)
            expert_bytes = (w.expert_param_count * bytes_per_param
                            / (pp * tp))
            param_local_bytes -= expert_bytes * (1 - 1 / ep)
        if dp > 1:
            out.append(Collective("psum", "data", param_local_bytes, dp,
                                  overlappable=True))
        if pp > 1:
            out.append(Collective("ppermute", "stage", micro_act, pp,
                                  count=2 * (M + pp - 1)))
        if tp > 1:
            out.append(Collective("psum", "model", micro_act, tp,
                                  count=4 * layers_local * M))
        if sp > 1:
            out.append(Collective("all_to_all", "seq", micro_act, sp,
                                  count=4 * layers_local * M))
        if ep > 1:
            out.append(Collective("all_to_all", "expert",
                                  micro_act * w.moe_top_k, ep,
                                  count=4 * layers_local * M))
    elif w.kind == "cnn":
        if plan.strategy == "fsdp":
            out.append(Collective("all_gather", "data", w.param_bytes,
                                  dp, count=2))
            out.append(Collective("reduce_scatter", "data", w.param_bytes,
                                  dp, overlappable=True))
        elif dp > 1:
            out.append(Collective("psum", "data", w.param_bytes, dp,
                                  overlappable=True))
        if pp > 1:
            micro_act = micro_b * w.boundary_act_bytes_per_sample
            out.append(Collective("ppermute", "stage", micro_act, pp,
                                  count=2 * (M + pp - 1)))
    else:
        raise KeyError(f"unknown workload kind {w.kind!r}")
    return out


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Scored plan: the ranker sorts by ``total_s`` (ties broken by the
    plan tuple itself — plan.py's ordered dataclass). ``comm_s`` is the
    full collective time, ``comm_hidden_s`` the part credited as
    overlapped with the backward; ``total_s`` charges only the exposed
    remainder."""

    compute_s: float
    comm_s: float
    comm_hidden_s: float
    bubble: float
    total_s: float

    def payload(self) -> dict:
        return {"compute_s": self.compute_s, "comm_s": self.comm_s,
                "comm_hidden_s": self.comm_hidden_s,
                "bubble": self.bubble, "total_s": self.total_s}


def bubble_factor(plan: ParallelPlan) -> float:
    """GPipe/1F1B steady-state bubble multiplier (1.0 off-pipeline)."""
    if plan.pp <= 1:
        return 1.0
    M = max(1, plan.num_microbatches)
    return (M + plan.pp - 1) / M


def plan_cost(w: WorkloadSpec, plan: ParallelPlan,
              coeffs: CostCoefficients | None = None, *,
              observed: Mapping[str, Mapping[str, float]] | None = None
              ) -> PlanCost:
    """Alpha-beta score of one plan.

    ``observed`` ({axis: {"bytes": ..., "ops": ...}} from
    :func:`observed_comm_table`) overrides the analytic volume on
    matching axes: the trace-time accounting of a real step beats the
    model where both exist.
    """
    coeffs = coeffs if coeffs is not None else CostCoefficients()
    flop_shards = plan.dp * plan.pp * plan.tp * max(1, plan.sp)
    compute_s = (w.flops_per_step / flop_shards) / coeffs.peak_flops_per_s
    bubble = bubble_factor(plan)
    # Group analytically per axis first: an observed per-axis total
    # replaces the axis's analytic time as a whole, and its overlap
    # credit is apportioned by the ANALYTIC overlappable share of that
    # axis (the trace-time counters don't distinguish grad reductions
    # from forward gathers, so e.g. FSDP's reduce-scatter keeps its
    # credit under observed re-ranking).
    analytic: dict[str, list[float]] = {}   # axis -> [total, overlappable]
    for c in plan_collectives(w, plan):
        t = collective_time_s(c, coeffs)
        bucket = analytic.setdefault(c.axis, [0.0, 0.0])
        bucket[0] += t
        if c.overlappable:
            bucket[1] += t
    comm_s = 0.0
    overlappable_s = 0.0
    for axis, (total_t, over_t) in sorted(analytic.items()):
        if observed and axis in observed:
            obs = observed[axis]
            t = (coeffs.alpha_s * float(obs.get("ops", 0.0))
                 + float(obs.get("bytes", 0.0)) / coeffs.wire_bytes_per_s)
            frac = over_t / total_t if total_t > 0 else 0.0
            comm_s += t
            overlappable_s += t * frac
        else:
            comm_s += total_t
            overlappable_s += over_t
    hidden = min(overlappable_s,
                 coeffs.overlap_fraction * compute_s * bubble)
    total = compute_s * bubble + comm_s - hidden
    return PlanCost(compute_s=compute_s, comm_s=comm_s,
                    comm_hidden_s=hidden, bubble=bubble, total_s=total)


def observed_comm_table(counters: Mapping[str, float] | None = None
                        ) -> dict[str, dict[str, float]]:
    """Per-axis comm volume observed by the trace-time accounting:
    ``{axis: {"bytes": wire-bytes-est total, "ops": ops-est total}}``.

    ``counters`` is a flat counter mapping — either
    ``registry().snapshot()["counters"]`` (the live process) or the
    ``counters`` block of a telemetry ``metrics`` record (a finished
    run's stream). Defaults to the live registry. Keys look like
    ``collective_wire_bytes_est{axis=data,kind=psum}``; kinds are summed
    per axis (the cost model consumes per-axis totals).
    """
    if counters is None:
        from distributed_model_parallel_tpu.utils.telemetry import registry

        counters = registry().snapshot()["counters"]
    out: dict[str, dict[str, float]] = {}
    fields = {"collective_wire_bytes_est": "bytes",
              "collective_ops_est": "ops"}
    for key, val in counters.items():
        name, _, tags = key.partition("{")
        if name not in fields or not tags.endswith("}"):
            continue
        tag_map = dict(t.split("=", 1) for t in tags[:-1].split(",")
                       if "=" in t)
        axis = tag_map.get("axis")
        if axis is None:
            continue
        bucket = out.setdefault(axis, {"bytes": 0.0, "ops": 0.0})
        bucket[fields[name]] += float(val)
    return out
