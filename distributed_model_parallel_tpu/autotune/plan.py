"""The typed parallel plan: one layout the planner can propose or a run
can report.

A plan is the 6-tuple the whole strategy zoo composes from — data (dp),
pipeline (pp), tensor (tp), sequence (sp) and expert (ep) degrees plus the
engine ``strategy`` that drives the data axis ("gspmd" | "ddp" | "fsdp" |
"spmd_pipeline" for the CNN trainers, "spmd" for the LM SPMD program) —
and the microbatch count when a pipeline axis is active. The same payload
shape appears in three places so artifacts stay joinable:

* the ``plan`` telemetry record (autotune/planner.emit_plan_record);
* bench.py's headline JSON (every BENCH_*/MULTICHIP_* record embeds the
  active plan, so artifacts are self-describing);
* ``scripts/dmp_plan.py``'s ranked output.
"""

from __future__ import annotations

import dataclasses

from distributed_model_parallel_tpu.config import MeshConfig

__all__ = ["ParallelPlan", "mesh_from_plan", "plan_payload"]


@dataclasses.dataclass(frozen=True, order=True)
class ParallelPlan:
    """One candidate (strategy, dp, pp, tp, sp, ep, M) layout.

    Ordered (field order above) so deterministic tie-breaking in the
    ranker is a plain tuple compare, never dict/hash order.
    """

    strategy: str
    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    num_microbatches: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.tp * self.sp * self.ep

    def axes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "tp": self.tp,
                "sp": self.sp, "ep": self.ep}

    def describe(self) -> str:
        degrees = "x".join(f"{k}{v}" for k, v in self.axes().items()
                           if v > 1) or "dp1"
        tail = (f" M={self.num_microbatches}"
                if self.pp > 1 and self.num_microbatches > 1 else "")
        return f"{self.strategy}[{degrees}]{tail}"

    def payload(self) -> dict:
        """JSON payload shared by telemetry/bench/CLI (module docstring)."""
        return {"strategy": self.strategy, "axes": self.axes(),
                "num_microbatches": self.num_microbatches}


def mesh_from_plan(plan: ParallelPlan,
                   base: MeshConfig | None = None) -> MeshConfig:
    """The plan's axis degrees over ``base``'s axis names.

    The dcn factor survives only when it still divides the planned dp —
    the same keep-or-drop rule as ``train/elastic.fit_mesh_to_devices``
    (a re-planned slice's host layout is unknown).
    """
    base = base if base is not None else MeshConfig()
    dcn = base.dcn_data if base.dcn_data > 1 and plan.dp % base.dcn_data == 0 \
        else 1
    return dataclasses.replace(base, data=plan.dp, stage=plan.pp,
                               model=plan.tp, seq=plan.sp, expert=plan.ep,
                               dcn_data=dcn)


def plan_payload(mesh: MeshConfig, strategy: str, *,
                 num_microbatches: int = 1) -> dict:
    """The plan payload for a run that already HAS a mesh (bench.py's
    headline records): same shape as ``ParallelPlan.payload`` so the
    planner's measured-validation records and the bench artifacts are one
    schema."""
    return ParallelPlan(
        strategy=strategy, dp=mesh.data, pp=mesh.stage, tp=mesh.model,
        sp=mesh.seq, ep=mesh.expert,
        num_microbatches=num_microbatches).payload()
