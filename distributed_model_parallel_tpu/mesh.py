"""Device discovery and mesh construction.

Replaces the reference's process bootstrap — ``mp.spawn`` +
``dist.init_process_group('nccl', 'tcp://127.0.0.1:1224')`` +
``torch.cuda.set_device(rank)`` (reference ``model_parallel.py:57-62,162``) —
with the TPU-native runtime: ``jax.distributed.initialize`` for multi-host
rendezvous and a ``jax.sharding.Mesh`` with named axes for everything else.
All parallelism in this framework is expressed as PartitionSpecs over these
axes; XLA inserts the collectives (psum/ppermute/all_gather) over ICI/DCN.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.config import MeshConfig

logger = logging.getLogger(__name__)

# Name of the cross-host (slow-network) sub-axis of data parallelism; it
# exists in the mesh only when MeshConfig.dcn_data > 1.
DCN_AXIS = "dcn"


def best_effort_distributed_init() -> bool:
    """Initialize the multi-host JAX runtime if the environment asks for it.

    The reference requires explicit ``--dist-url``/``--world-size`` flags and a
    TCP rendezvous even on one node (``model_parallel.py:19-24,57``). On TPU,
    single-host needs nothing, and multi-host pods are auto-detected by
    ``jax.distributed.initialize()`` from the cluster environment. Returns True
    if a multi-process runtime was initialized.
    """
    want = os.environ.get("DMP_TPU_DISTRIBUTED", "auto")
    if want == "0":
        return False
    try:
        if jax.process_count() > 1:
            return True  # already initialized
    except Exception as e:
        # Backend unreachable: don't traceback out of the probe — the
        # caller's hardened device contact (utils/device_contact.py)
        # owns the retry/parseable-failure-record policy.
        logger.warning("backend probe failed during distributed init: %s", e)
        return False
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if want == "1" or coordinator:
        try:
            jax.distributed.initialize()
            return True
        except Exception as e:  # pragma: no cover - environment dependent
            logger.warning("jax.distributed.initialize failed: %s", e)
    return False


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A constructed mesh plus canonical PartitionSpecs.

    Axis order is (data, stage, model, seq, expert) with size-1 axes kept in
    the mesh (they cost nothing and keep PartitionSpecs uniform).
    """

    mesh: Mesh
    config: MeshConfig

    # -- canonical axis names ------------------------------------------------
    @property
    def data_axis(self) -> str | tuple[str, str]:
        """Axis (or axes) replicas span. With ``dcn_data > 1`` the mesh has a
        real leading ``"dcn"`` axis and this returns ``("dcn", data_axis)`` —
        PartitionSpecs and collectives accept the tuple everywhere a single
        name is legal, so DP/DDP/FSDP code is hierarchy-agnostic, while
        two-level code can address ``dcn_axis``/``ici_data_axis`` separately.
        """
        if self.config.dcn_data > 1:
            return (DCN_AXIS, self.config.data_axis)
        return self.config.data_axis

    @property
    def data_axes(self) -> tuple[str, ...]:
        """``data_axis`` normalized to a tuple — the spelling collectives
        and shard_map axis lists want regardless of whether the data axis
        is flat or dcn-factored. ``num_data`` is the replica count over
        exactly these axes (the dcn factor included)."""
        da = self.data_axis
        return (da,) if isinstance(da, str) else tuple(da)

    @property
    def dcn_axis(self) -> str | None:
        """The cross-host sub-axis of data parallelism (None on one host)."""
        return DCN_AXIS if self.config.dcn_data > 1 else None

    @property
    def ici_data_axis(self) -> str:
        """The within-host sub-axis of data parallelism."""
        return self.config.data_axis

    @property
    def stage_axis(self) -> str:
        return self.config.stage_axis

    @property
    def model_axis(self) -> str:
        return self.config.model_axis

    @property
    def seq_axis(self) -> str:
        return self.config.seq_axis

    @property
    def expert_axis(self) -> str:
        return self.config.expert_axis

    # -- canonical shardings -------------------------------------------------
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharded(self) -> NamedSharding:
        """Batch-dim sharding: the TPU equivalent of DataParallel's ``scatter``
        (reference ``Readme.md:20,28-29``)."""
        return NamedSharding(self.mesh, P(self.data_axis))

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def num_data(self) -> int:
        return self.config.data

    @property
    def num_stages(self) -> int:
        return self.config.stage

    def stage_devices(self) -> list[jax.Device]:
        """One representative device per pipeline stage (data index 0).

        Used by the per-stage pipeline runtime (parallel/pipeline.py) for
        computation-follows-data placement.
        """
        devs = np.asarray(self.mesh.devices)
        axes = list(self.mesh.axis_names)
        idx = [slice(None) if a == self.stage_axis else 0 for a in axes]
        return list(np.atleast_1d(devs[tuple(idx)]).ravel())


def make_mesh(config: MeshConfig | None = None,
              devices: Sequence[jax.Device] | None = None) -> MeshSpec:
    """Build a named mesh from a MeshConfig.

    If ``config`` is None, all local devices go on the data axis — mirroring
    the reference's default of one DP replica per visible GPU
    (``data_parallel.py:77``, ``model_parallel.py:20``).
    """
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(data=len(devices))
    n = config.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices ({config.axis_sizes()}), "
            f"only {len(devices)} available")
    shape = (config.data, config.stage, config.model, config.seq, config.expert)
    names = (config.data_axis, config.stage_axis, config.model_axis,
             config.seq_axis, config.expert_axis)
    if config.dcn_data < 1:
        raise ValueError(f"dcn_data must be >= 1, got {config.dcn_data}")
    if config.dcn_data > 1:
        # The data axis factors into a real leading "dcn" (cross-host) axis
        # and a within-host remainder, so shardings can span both
        # (MeshSpec.data_axis) and collectives can stage hierarchically.
        if config.data % config.dcn_data:
            raise ValueError(
                f"dcn_data={config.dcn_data} must divide data={config.data}")
        if DCN_AXIS in names:
            raise ValueError(f"axis name {DCN_AXIS!r} is reserved for dcn_data")
        shape = (config.dcn_data, config.data // config.dcn_data) + shape[1:]
        names = (DCN_AXIS,) + names
        if jax.process_count() > 1:
            # Real multi-host: let mesh_utils place the DCN granules along
            # process boundaries and optimize the ICI layout within each.
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_hybrid_device_mesh(
                shape[1:], (config.dcn_data, 1, 1, 1, 1),
                devices=devices[:n], process_is_granule=True).reshape(shape)
            return MeshSpec(mesh=Mesh(grid, names), config=config)
    # Single process (or flat mesh): contiguous device-id blocks stand in
    # for hosts — the leading (dcn, data) reshape is host-major by
    # construction.
    grid = np.asarray(devices[:n]).reshape(shape)
    return MeshSpec(mesh=Mesh(grid, names), config=config)


def host_local_batch_to_global(batch, spec: MeshSpec,
                               sharding: NamedSharding | None = None):
    """Assemble a global sharded array from per-process local data.

    Multi-host form of the reference's rank-0-only data loading
    (``model_parallel.py:89-97`` loads on every rank and uses it on one):
    each host loads only its slice of the global batch and
    ``jax.make_array_from_process_local_data`` stitches the global
    ``jax.Array`` across hosts. On a single process this degenerates to a
    plain ``device_put``.
    """
    if sharding is None:
        sharding = spec.batch_sharded()
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch)


def local_batch_slice(global_batch: int, spec: MeshSpec) -> int:
    """Per-data-shard batch size; errors on uneven split (static shapes)."""
    d = spec.num_data
    if global_batch % d:
        raise ValueError(f"global batch {global_batch} not divisible by data={d}")
    return global_batch // d


class StragglerTimeoutError(RuntimeError):
    """A barrier/collective did not complete within its budget: one
    participant (host or device) is wedged or gone. Raised by
    :func:`barrier_with_timeout` so the caller reports a straggler event
    instead of hanging forever — the reference's failure mode
    (``dist.recv`` blocks eternally on a dead rank,
    ``distributed_layers.py:20``)."""


def barrier_with_timeout(fn, timeout_s: float, *, what: str = "barrier",
                         on_timeout=None):
    """Run the blocking rendezvous ``fn()`` with a wall-clock budget.

    ``fn`` (e.g. ``ops.collectives.mesh_barrier``) runs on a daemon worker
    thread; if it completes within ``timeout_s`` its result is returned
    (or its exception re-raised). On timeout, ``on_timeout(what,
    timeout_s)`` is invoked (telemetry hook) and
    :class:`StragglerTimeoutError` is raised. The wedged call itself
    cannot be cancelled — the worker thread is left blocked (daemonized,
    so it never holds up process exit); the point is that the *caller*
    gets control back to record the straggler and escalate, instead of
    inheriting the hang.
    """
    import threading

    box: dict = {}
    done = threading.Event()

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised on the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"dmp-barrier-{what}")
    t.start()
    if not done.wait(timeout_s):
        if on_timeout is not None:
            on_timeout(what, timeout_s)
        raise StragglerTimeoutError(
            f"{what} did not complete within {timeout_s:.1f}s — a "
            f"participant is wedged or missing (straggler)")
    if "error" in box:
        raise box["error"]
    return box.get("result")
