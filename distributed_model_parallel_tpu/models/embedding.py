"""Bag-of-words embedding classifier — the sparse-gradient DDP workload.

BASELINE.json config 5: "sparse-gradient DDP path (nn.Embedding bag-of-words
classifier, sparse=True)". Mean-pooled token embeddings + linear head. The
model is deliberately tiny-dense-head / huge-sparse-table so the embedding
gradient path (ops/sparse.py) dominates, like its torch counterpart.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import optax

from distributed_model_parallel_tpu.ops.sparse import (
    apply_sparse_grad,
    embedding_grad_sparse,
    embedding_lookup,
    sparse_allreduce,
)


@dataclasses.dataclass(frozen=True)
class BowConfig:
    vocab_size: int = 10000
    embed_dim: int = 64
    num_classes: int = 10


def init_params(rng: jax.Array, cfg: BowConfig) -> dict:
    k1, k2 = jax.random.split(rng)
    return {
        "embedding": jax.random.normal(k1, (cfg.vocab_size, cfg.embed_dim)) * 0.1,
        "w": jax.random.normal(k2, (cfg.embed_dim, cfg.num_classes))
             * (cfg.embed_dim ** -0.5),
        "b": jnp.zeros((cfg.num_classes,)),
    }


def apply(params: dict, tokens: jax.Array) -> jax.Array:
    """[B, T] int tokens -> [B, C] logits (mean-pooled bag of words)."""
    emb = embedding_lookup(params["embedding"], tokens)
    pooled = jnp.mean(emb, axis=1)
    return pooled @ params["w"] + params["b"]


def loss_fn(params: dict, tokens: jax.Array, labels: jax.Array) -> jax.Array:
    logits = apply(params, tokens)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_sparse_sgd_step(cfg: BowConfig, lr: float, axis_name: str | None = None):
    """SGD step where the embedding gradient stays COO end-to-end.

    Dense params (w, b) take the ordinary (psum-averaged) dense gradient;
    the table takes a scatter-add sparse update. With ``axis_name`` set the
    step must run inside shard_map over that axis and performs the DDP-style
    sparse allreduce.
    """

    def head_loss(head, pooled, labels):
        logits = pooled @ head["w"] + head["b"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    def step(params, tokens, labels):
        b, t = tokens.shape
        emb = embedding_lookup(params["embedding"], tokens)
        pooled = jnp.mean(emb, axis=1)
        head = {"w": params["w"], "b": params["b"]}
        loss, (dense_grads, d_pooled) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(head, pooled, labels)
        # d(emb) = d_pooled / T broadcast over the T axis -> COO directly.
        d_emb = jnp.broadcast_to(d_pooled[:, None] / t, (b, t, d_pooled.shape[-1]))
        ids, vals = embedding_grad_sparse(tokens, d_emb)

        if axis_name is not None:
            dense_grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, axis_name), dense_grads)
            ids, vals = sparse_allreduce(ids, vals, axis_name)
            loss = jax.lax.pmean(loss, axis_name)

        new_params = {
            "embedding": apply_sparse_grad(params["embedding"], ids, vals, lr),
            "w": params["w"] - lr * dense_grads["w"],
            "b": params["b"] - lr * dense_grads["b"],
        }
        return new_params, loss

    return step


def build_embedding_bow(model_config) -> BowConfig:
    """Registry adapter (ModelConfig.extra carries BowConfig fields)."""
    extra = dict(model_config.extra)
    extra.setdefault("num_classes", model_config.num_classes)
    return BowConfig(**extra)
