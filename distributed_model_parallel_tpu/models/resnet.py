"""ResNet-18/34/50, CIFAR-adapted, as staged unit sequences.

The reference's DP driver lists ResNet in its (commented-out) model menu
(``data_parallel.py:58-73``) and BASELINE.json promotes ResNet-18 (config 1)
and ResNet-50 (configs 2-3, the north-star throughput metric) to in-scope.
CIFAR adaptation follows the same convention as the reference's MobileNetV2
(stride-1 3x3 stem, no max-pool; ``model/mobilenetv2.py:42,51``).

Units: stem, then one unit per residual block (8 for R18, 16 for R50),
then head — so pipeline partitioning is uniform with MobileNetV2.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.layers import ClassifierHead, ConvUnit, _norm
from distributed_model_parallel_tpu.models.staged import StagedModel

# name -> (block kind, blocks per group)
ARCH = {
    "resnet18": ("basic", (2, 2, 2, 2)),
    "resnet34": ("basic", (3, 4, 6, 3)),
    "resnet50": ("bottleneck", (3, 4, 6, 3)),
}
GROUP_FEATURES = (64, 128, 256, 512)


class ResBlock(nn.Module):
    """Basic (3x3,3x3) or bottleneck (1x1,3x3,1x1 x4) residual block."""

    kind: str                # "basic" | "bottleneck"
    features: int            # base width of the group
    stride: int
    bn_mode: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: Any = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, *, train: bool):
        use_bias = self.bn_mode == "none"
        out_features = self.features * (4 if self.kind == "bottleneck" else 1)

        def norm(name):
            return _norm(self.bn_mode, momentum=self.bn_momentum,
                         epsilon=self.bn_epsilon, dtype=self.dtype,
                         axis_name=self.axis_name, name=name)

        y = x
        if self.kind == "basic":
            specs = [(self.features, 3, self.stride), (self.features, 3, 1)]
        else:
            specs = [(self.features, 1, 1), (self.features, 3, self.stride),
                     (out_features, 1, 1)]
        for i, (f, k, s) in enumerate(specs):
            y = nn.Conv(f, (k, k), strides=(s, s), padding="SAME",
                        use_bias=use_bias, dtype=self.dtype, name=f"conv{i}")(y)
            y = norm(f"bn{i}")(y, train)
            if i < len(specs) - 1:
                y = nn.relu(y)

        if self.stride != 1 or x.shape[-1] != out_features:
            x = nn.Conv(out_features, (1, 1), strides=(self.stride,) * 2,
                        use_bias=use_bias, dtype=self.dtype, name="shortcut")(x)
            x = norm("shortcut_bn")(x, train)
        return nn.relu(y + x)


def build_resnet(arch: str = "resnet18", num_classes: int = 10, *,
                 bn_mode: str = "local", bn_momentum: float = 0.9,
                 bn_epsilon: float = 1e-5, dtype: Any = jnp.float32,
                 axis_name: str | None = None,
                 input_layout: str = "cifar") -> StagedModel:
    """``input_layout="imagenet"`` = the standard stem (7x7 stride-2 conv +
    3x3 stride-2 max-pool) for native-resolution (224px) inputs;
    ``"cifar"`` = the 32px adaptation (3x3 stride-1 stem, no pool)."""
    if input_layout not in ("cifar", "imagenet"):
        raise ValueError(f"unknown input_layout: {input_layout!r}")
    imagenet = input_layout == "imagenet"
    kind, groups = ARCH[arch]
    common = dict(bn_mode=bn_mode, bn_momentum=bn_momentum,
                  bn_epsilon=bn_epsilon, dtype=dtype, axis_name=axis_name)
    stem_op = ({"features": 64, "kernel": 7, "stride": 2, "maxpool": 2}
               if imagenet else {"features": 64, "kernel": 3, "stride": 1})
    units: list[nn.Module] = [ConvUnit(ops=(stem_op,), **common)]
    for g, num_blocks in enumerate(groups):
        for b in range(num_blocks):
            units.append(ResBlock(
                kind=kind, features=GROUP_FEATURES[g],
                stride=(2 if g > 0 and b == 0 else 1), **common))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **common))
    name = arch + ("_imagenet" if imagenet else "")
    return StagedModel(units=tuple(units), name=name)
