"""Torch state_dict → staged-model weight import.

The reference trains torch models and resumes from torch checkpoints
(``{'net': state_dict, 'acc': ..., 'epoch': ...}`` written by its DP driver,
reference ``data_parallel.py:84-87``). A user migrating mid-experiment
therefore owns torch weights; this module maps them onto a ``StagedModel``'s
flax pytrees so training (or eval) continues on TPU from the same numbers.

The mapping is *structural*, not name-based: both frameworks register
modules in execution order (torch: ``__init__`` registration order, which a
``state_dict``'s insertion order preserves; flax: ``nn.compact`` creation
order, which the params dict preserves), so the importer walks both sides as
a sequence of typed records — conv / linear / norm — and pairs them up in
order. Every pairing is shape-checked after layout conversion, so a
misaligned walk fails loudly with both names in the error rather than
silently loading a transposed layer. Layout conversions:

* conv weight  ``(O, I/g, kH, kW)`` → ``(kH, kW, I/g, O)``  (NCHW → NHWC;
  the same transpose covers depthwise convs, where torch's per-channel
  ``(C, 1, kH, kW)`` becomes flax's ``feature_group_count`` form
  ``(kH, kW, 1, C)``)
* linear weight ``(O, I)`` → ``(I, O)``
* batchnorm ``weight/bias/running_mean/running_var`` →
  ``scale/bias`` (params) + ``mean/var`` (batch_stats);
  ``num_batches_tracked`` is dropped (flax keeps no step counter)

Caveat: a torch ``Flatten`` of an ``(N, C, H, W)`` tensor with H*W > 1
orders features C-major while an NHWC flatten orders them C-minor, so a
linear layer *after* such a flatten needs its input dim permuted. The zoo's
heads all pool to (N, C) before the linear (``models/layers.py:110``), where
the two orders coincide; the importer cannot see pre-flatten shapes, so it
does not attempt the permutation.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Iterator

import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.staged import Params, StagedModel, State


def _to_numpy(t) -> np.ndarray:
    """torch.Tensor | array-like -> np.ndarray (no torch import needed)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def strip_prefix(state_dict: Mapping[str, Any],
                 prefix: str = "module.") -> dict[str, Any]:
    """Remove a wrapper prefix (torch ``DataParallel``/``DistributedDataParallel``
    register the wrapped net under ``module.``) from every key carrying it."""
    return {(k[len(prefix):] if k.startswith(prefix) else k): v
            for k, v in state_dict.items()}


# ---------------------------------------------------------------------------
# torch side: group flat keys into typed module records
# ---------------------------------------------------------------------------

def _torch_records(state_dict: Mapping[str, Any]) -> list[dict]:
    """Group ``a.b.weight``-style keys by module prefix, in first-appearance
    order, and classify each group as conv / linear / norm."""
    groups: dict[str, dict[str, np.ndarray]] = {}
    for key, value in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        prefix, _, leaf = key.rpartition(".")
        groups.setdefault(prefix, {})[leaf] = _to_numpy(value)
    records = []
    for name, tensors in groups.items():
        if "running_mean" in tensors or (
                "weight" in tensors and tensors["weight"].ndim == 1):
            kind = "norm"
        elif "weight" in tensors and tensors["weight"].ndim == 4:
            kind = "conv"
        elif "weight" in tensors and tensors["weight"].ndim == 2:
            kind = "linear"
        else:
            shapes = {k: v.shape for k, v in tensors.items()}
            raise ValueError(
                f"cannot classify torch module {name!r} with tensors "
                f"{shapes}; expected a conv (4-d weight), linear (2-d "
                f"weight), or norm (1-d weight / running stats)")
        records.append({"name": name or "<root>", "kind": kind,
                        "tensors": tensors})
    return records


# ---------------------------------------------------------------------------
# flax side: walk the staged trees into typed module records
# ---------------------------------------------------------------------------

def _is_module_leaf(d: Mapping[str, Any]) -> bool:
    return any(not isinstance(v, Mapping) for v in d.values())


def _walk_modules(tree: Mapping[str, Any], path: str) -> Iterator[tuple[str, Any]]:
    """Yield (dotted-path, leaf-module dict) in insertion (= creation) order."""
    for key, value in tree.items():
        sub = f"{path}.{key}" if path else key
        if isinstance(value, Mapping) and value:
            if _is_module_leaf(value):
                yield sub, value
            else:
                yield from _walk_modules(value, sub)


def _flax_records(model: StagedModel, params: Params, state: State) -> list[dict]:
    """Typed records for every conv/dense/norm module across the units, in
    execution order, each carrying setters into (new_params, new_state)."""
    records = []
    for i in range(model.num_units):
        for path, leaves in _walk_modules(params[i], f"unit{i}"):
            if "kernel" in leaves:
                kind = "conv" if np.ndim(leaves["kernel"]) == 4 else "linear"
            elif "scale" in leaves or "bias" in leaves:
                kind = "norm"
            else:
                raise ValueError(
                    f"cannot classify flax module {path!r} with leaves "
                    f"{list(leaves)}")
            records.append({"name": path, "kind": kind, "unit": i,
                            "params": leaves, "stats": None})
        for path, leaves in _walk_modules(state[i], f"unit{i}"):
            # Attach running stats to the norm record of the same path.
            for rec in records:
                if rec["name"] == path and rec["kind"] == "norm":
                    rec["stats"] = leaves
                    break
            else:
                raise ValueError(f"batch_stats at {path!r} with no matching "
                                 f"norm params")
    return records


# ---------------------------------------------------------------------------
# pairing + conversion
# ---------------------------------------------------------------------------

def _convert(torch_rec: dict, flax_rec: dict) -> tuple[dict, dict | None]:
    """Convert one torch module's tensors into the flax record's layout.
    Returns (new_params_leaves, new_stats_leaves | None)."""
    t = torch_rec["tensors"]
    f = flax_rec["params"]

    def check(name, got, want):
        if tuple(got.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"shape mismatch importing torch {torch_rec['name']!r} -> "
                f"flax {flax_rec['name']!r} ({name}): converted "
                f"{tuple(got.shape)} vs expected {tuple(np.shape(want))} — "
                f"the walks are misaligned or the architectures differ")
        return jnp.asarray(got, dtype=np.asarray(want).dtype)

    if flax_rec["kind"] == "conv":
        out = {"kernel": check("kernel", t["weight"].transpose(2, 3, 1, 0),
                               f["kernel"])}
        if "bias" in f:
            if "bias" not in t:
                raise ValueError(
                    f"flax conv {flax_rec['name']!r} has a bias but torch "
                    f"{torch_rec['name']!r} does not")
            out["bias"] = check("bias", t["bias"], f["bias"])
        return out, None
    def require(leaf):
        if leaf not in t:
            raise ValueError(
                f"flax module {flax_rec['name']!r} has a {leaf!r} but torch "
                f"{torch_rec['name']!r} does not (keys: {sorted(t)})")
        return t[leaf]

    if flax_rec["kind"] == "linear":
        out = {"kernel": check("kernel", t["weight"].T, f["kernel"])}
        if "bias" in f:
            out["bias"] = check("bias", require("bias"), f["bias"])
        return out, None
    # norm
    out = {}
    if "scale" in f:
        out["scale"] = check("scale", require("weight"), f["scale"])
    if "bias" in f:
        out["bias"] = check("bias", require("bias"), f["bias"])
    stats = None
    if flax_rec["stats"] is not None:
        stats = {"mean": check("mean", t["running_mean"],
                               flax_rec["stats"]["mean"]),
                 "var": check("var", t["running_var"],
                              flax_rec["stats"]["var"])}
    return out, stats


def _set_path(tree: dict, path: list[str], leaves: dict) -> dict:
    """Functionally replace the dict at ``path`` inside ``tree``."""
    if not path:
        return {**tree, **leaves}
    head, *rest = path
    return {**tree, head: _set_path(tree[head], rest, leaves)}


def from_torch_state_dict(model: StagedModel, params: Params, state: State,
                          state_dict: Mapping[str, Any]) -> tuple[Params, State]:
    """Map a torch ``state_dict`` onto staged flax trees.

    ``params``/``state`` are the target trees (e.g. fresh ``model.init``
    output) — they fix the expected module order, shapes, and dtypes.
    Returns new ``(params, state)`` with every conv/linear/norm leaf
    replaced by the converted torch weights. Raises ``ValueError`` with
    both module names on any count, kind, or shape mismatch.

    ``module.``-prefixed keys (torch ``DataParallel`` wrappers, as the
    reference's checkpoints carry) are stripped automatically.
    """
    state_dict = strip_prefix(dict(state_dict))
    torch_recs = _torch_records(state_dict)
    flax_recs = _flax_records(model, params, state)
    if len(torch_recs) != len(flax_recs):
        t_names = [f"{r['kind']}:{r['name']}" for r in torch_recs]
        f_names = [f"{r['kind']}:{r['name']}" for r in flax_recs]
        raise ValueError(
            f"module count mismatch: torch state_dict has {len(torch_recs)} "
            f"conv/linear/norm modules, the staged model has "
            f"{len(flax_recs)}.\n torch: {t_names}\n flax: {f_names}")

    new_params = [dict(p) if isinstance(p, Mapping) else p for p in params]
    new_state = [dict(s) if isinstance(s, Mapping) else s for s in state]
    for t_rec, f_rec in zip(torch_recs, flax_recs):
        if t_rec["kind"] != f_rec["kind"]:
            raise ValueError(
                f"module kind mismatch at torch {t_rec['name']!r} "
                f"({t_rec['kind']}) vs flax {f_rec['name']!r} "
                f"({f_rec['kind']}) — the walks are misaligned")
        leaves, stats = _convert(t_rec, f_rec)
        unit = f_rec["unit"]
        # Path inside the unit subtree (strip the synthetic "unitN" head).
        rel = f_rec["name"].split(".")[1:]
        new_params[unit] = _set_path(new_params[unit], rel, leaves)
        if stats is not None:
            new_state[unit] = _set_path(new_state[unit], rel, stats)
    return tuple(new_params), tuple(new_state)


def load_torch_checkpoint(path: str) -> dict[str, Any]:
    """Read a torch checkpoint file and return its weight ``state_dict``.

    Accepts both a bare ``state_dict`` and the reference's wrapped format
    ``{'net': state_dict, 'acc': ..., 'epoch': ...}`` (reference
    ``data_parallel.py:84-87``; also tries the common ``'state_dict'`` /
    ``'model'`` wrapper keys). torch is imported lazily — the framework has
    no hard torch dependency.
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, Mapping) and not any(
            hasattr(v, "detach") or isinstance(v, np.ndarray)
            for v in obj.values()):
        for key in ("net", "state_dict", "model"):
            if key in obj:
                return dict(obj[key])
        raise ValueError(
            f"checkpoint at {path!r} has no tensor values and none of the "
            f"known wrapper keys ('net', 'state_dict', 'model'); keys: "
            f"{list(obj)[:10]}")
    return dict(obj)
