"""MobileNetV2, CIFAR-adapted, as a staged unit sequence.

Capability parity with the reference's ``model/mobilenetv2.py``:

* CIFAR adaptation — stem conv is stride 1 (not 2) and the first bottleneck
  group is stride 1; final pooling window is 4 (32px → 2x2 feature map at
  the head in the reference's NCHW layout; we use a global average pool which
  is identical for 32px inputs). Reference notes the changes at
  ``model/mobilenetv2.py:42,51,72``.
* cfg table: (expansion, out_channels, num_blocks, stride) x 7 groups summing
  to 17 inverted-residual blocks (``model/mobilenetv2.py:41-47``), which makes
  the model a flat stage-able sequence — here 19 units: stem, 17 blocks, head.
* Inverted residual block: expand 1x1 → depthwise 3x3 → project 1x1, BN after
  each, residual add iff stride == 1, with a projected shortcut when channel
  counts differ (``model/mobilenetv2.py:10-36``).
* ``bn_mode="none"`` builds the no-BatchNorm variant used by the reference's
  large-batch study (``MobileNetV2_nobn``, ``model/mobilenetv2.py:84-148``).
  Unlike the reference, the no-BN variant here contains *no* BN anywhere —
  the reference accidentally keeps one in the shortcut
  (``model/mobilenetv2.py:100-103``); we do not reproduce that quirk.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.layers import ClassifierHead, ConvUnit, _norm
from distributed_model_parallel_tpu.models.staged import StagedModel

# (expansion, out_channels, num_blocks, stride) — CIFAR-adapted MobileNetV2.
CFG = (
    (1, 16, 1, 1),
    (6, 24, 2, 1),   # stride 1 for CIFAR (2 for ImageNet)
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)

# Standard ImageNet strides (torchvision mobilenet_v2) — the architecture
# the reference's 224px finetune recipe runs (``Readme.md:186-205``): stem
# stride 2 and stride 2 in the second group, so 224px inputs reach the head
# as 7x7 maps instead of the CIFAR variant's 28x28.
CFG_IMAGENET = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class InvertedResidual(nn.Module):
    """Expand 1x1 → depthwise 3x3 → project 1x1, residual iff stride == 1."""

    expansion: int
    features: int
    stride: int
    bn_mode: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: Any = jnp.float32
    axis_name: str | None = None
    # "reference": the CIFAR block (unconditional expand conv; projected
    # 1x1+BN shortcut when channel counts differ at stride 1,
    # ``model/mobilenetv2.py:26-36``). "torchvision": the ImageNet block
    # (no expand conv at expansion 1; residual ONLY iff stride==1 and
    # in_features==features — no projection branch exists).
    style: str = "reference"

    @nn.compact
    def __call__(self, x, *, train: bool):
        in_features = x.shape[-1]
        hidden = in_features * self.expansion
        use_bias = self.bn_mode == "none"

        def norm(name):
            return _norm(self.bn_mode, momentum=self.bn_momentum,
                         epsilon=self.bn_epsilon, dtype=self.dtype,
                         axis_name=self.axis_name, name=name)

        if self.expansion == 1 and self.style == "torchvision":
            y = x
        else:
            y = nn.Conv(hidden, (1, 1), use_bias=use_bias, dtype=self.dtype,
                        name="expand")(x)
            y = norm("expand_bn")(y, train)
            y = nn.relu(y)
        y = nn.Conv(hidden, (3, 3), strides=(self.stride,) * 2, padding="SAME",
                    feature_group_count=hidden, use_bias=use_bias,
                    dtype=self.dtype, name="depthwise")(y)
        y = norm("depthwise_bn")(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.features, (1, 1), use_bias=use_bias, dtype=self.dtype,
                    name="project")(y)
        y = norm("project_bn")(y, train)

        if self.stride == 1:
            if in_features != self.features:
                if self.style == "torchvision":
                    return y          # no residual at all
                x = nn.Conv(self.features, (1, 1), use_bias=use_bias,
                            dtype=self.dtype, name="shortcut")(x)
                x = norm("shortcut_bn")(x, train)
            y = y + x
        return y


def build_mobilenetv2(num_classes: int = 10, *, bn_mode: str = "local",
                      bn_momentum: float = 0.9, bn_epsilon: float = 1e-5,
                      dtype: Any = jnp.float32,
                      axis_name: str | None = None,
                      input_layout: str = "cifar") -> StagedModel:
    """19 units: stem, 17 inverted-residual blocks, head.

    ``input_layout="imagenet"`` selects the standard stride table
    (stride-2 stem, CFG_IMAGENET) for native-resolution inputs — the
    224px finetune workload; ``"cifar"`` keeps the reference's 32px
    adaptation (``model/mobilenetv2.py:42,51``)."""
    if input_layout not in ("cifar", "imagenet"):
        raise ValueError(f"unknown input_layout: {input_layout!r}")
    imagenet = input_layout == "imagenet"
    common = dict(bn_mode=bn_mode, bn_momentum=bn_momentum,
                  bn_epsilon=bn_epsilon, dtype=dtype, axis_name=axis_name)
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 32, "kernel": 3,
                       "stride": 2 if imagenet else 1},), **common)
    ]
    for expansion, features, num_blocks, stride in (
            CFG_IMAGENET if imagenet else CFG):
        for b in range(num_blocks):
            units.append(InvertedResidual(
                expansion=expansion, features=features,
                stride=stride if b == 0 else 1,
                style="torchvision" if imagenet else "reference", **common))
    units.append(ClassifierHead(
        num_classes=num_classes, conv_features=1280, **common))
    name = "mobilenetv2" if bn_mode != "none" else "mobilenetv2_nobn"
    if imagenet:
        name += "_imagenet"
    return StagedModel(units=tuple(units), name=name)
