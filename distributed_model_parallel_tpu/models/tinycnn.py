"""A small staged CNN for fast tests and CPU smoke runs.

Not part of the reference's zoo; exists so the test suite (SURVEY.md §4's
invented-from-scratch strategy) can exercise every parallelism path in
seconds on the 8-virtual-CPU-device mesh without paying MobileNetV2 compile
times. Same staged-unit contract as the real models.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from distributed_model_parallel_tpu.models.layers import ClassifierHead, ConvUnit
from distributed_model_parallel_tpu.models.staged import StagedModel


def build_tinycnn(num_classes: int = 10, *, bn_mode: str = "local",
                  bn_momentum: float = 0.9, bn_epsilon: float = 1e-5,
                  dtype: Any = jnp.float32,
                  axis_name: str | None = None,
                  width: int = 16, depth: int = 4) -> StagedModel:
    """stem + ``depth`` conv units (stride 2 on the middle one) + head."""
    common = dict(bn_mode=bn_mode, bn_momentum=bn_momentum,
                  bn_epsilon=bn_epsilon, dtype=dtype, axis_name=axis_name)
    units = [ConvUnit(ops=({"features": width, "kernel": 3, "stride": 1},),
                      **common)]
    for i in range(depth):
        stride = 2 if i == depth // 2 else 1
        units.append(ConvUnit(
            ops=({"features": width, "kernel": 3, "stride": stride},),
            **common))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **common))
    return StagedModel(units=tuple(units), name="tinycnn")
