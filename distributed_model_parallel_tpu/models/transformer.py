"""Decoder-only Transformer LM — the multi-axis-parallelism flagship.

The reference's zoo is CNN-only, so this model exists for the capabilities the
framework must carry beyond it: tensor parallelism, single-program SPMD
pipelining (homogeneous stacked blocks), and long-context sequence parallelism
(ring attention / Ulysses). It is written as pure functions over an explicit
parameter pytree — not linen — because every parallel path wants direct
control of array layout:

* ``params["blocks"]`` holds all L blocks *stacked* on a leading axis, so
  ``lax.scan`` runs them on one device, the ``stage`` mesh axis shards them
  for the SPMD pipeline, and PartitionSpecs shard head/ffn dims for tensor
  parallelism (Megatron split: column-parallel qkv/ffn-in, row-parallel
  out/ffn-out with a trailing psum).
* attention dispatches on the bound sequence axis: full causal attention by
  default, ring attention inside a ``seq`` shard_map.

Pre-LN, GELU MLP, learned positional embeddings, weight-tied LM head kept
separate (simplicity > tying).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.ops.ring_attention import (
    full_attention,
    ring_attention,
)

# Length of the MoE stats vector every block's aux channel carries:
# [load-balance loss, router z-loss, drop rate] (ops/moe._route). Dense
# blocks carry zeros so the channel is shape-uniform across models.
AUX_STATS = 3


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 1024
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 4
    d_ff: int = 512
    max_seq_len: int = 256
    dtype: Any = jnp.float32
    # Parallelism hooks (None = off). These name mesh axes and only take
    # effect inside a shard_map that binds them.
    tp_axis: str | None = None     # tensor parallel: heads/ffn sharded
    sp_axis: str | None = None     # sequence parallel: ring attention
    sp_impl: str = "ring"          # "ring" | "ulysses"
    # Attention kernel for the non-sequence-parallel path: "auto" consults
    # the measured per-platform dispatch table
    # (ops/pallas_attention._DISPATCH_TABLE — v5e crossover: seq 1024 for
    # both bf16 and f32 with the streamed-K/V kernels). Training uses the
    # FlashAttention-2 backward kernels (score tiles recomputed from the
    # saved logsumexp), so neither direction materializes [T, T] in HBM;
    # fwd+bwd reaches 97 TFLOPS at seq 8k head-dim 128 bf16
    # (benchmarks/grad_sweep_r3_hd128.json; plain XLA cannot compile 8k
    # at all). "xla" / "flash" force one implementation.
    attn_impl: str = "auto"
    # Sliding-window (local) attention: each token attends the last W
    # positions. Training runs on the flash kernels' banded block-skipping
    # (compute O(T*W) both directions; requires attn_impl="flash", no
    # sequence-parallel axis); generation band-masks the prefill and the
    # KV-cache scores with the same (pos-W, pos] band.
    attn_window: int | None = None
    remat: bool = False            # jax.checkpoint each block: recompute
                                   # activations in backward (HBM for FLOPs —
                                   # the long-context memory lever)
    # Remat granularity when remat=True: "full" recomputes the whole block
    # in the backward; "dots" saves matmul/einsum outputs and recomputes
    # only the cheap elementwise ops (jax.checkpoint_policies.
    # dots_with_no_batch_dims_saveable) — most of full-remat's memory win
    # at a fraction of its recompute FLOPs, usually the better MFU point
    # for long-sequence training.
    remat_policy: str = "full"
    # Mixture-of-experts FFN (0 = dense). When > 0 every block's MLP is a
    # top-k routed MoE (ops/moe.py); ep_axis shards experts over the
    # ``expert`` mesh axis inside a shard_map. MoE replaces the FFN, so
    # tp_axis then only shards attention.
    moe_experts: int = 0
    moe_top_k: int = 1
    # Defaults from the committed capacity x aux x z sweep
    # (benchmarks/moe_sweep_r5.json): cf 1.5 + aux 0.05 + z 1e-3 reaches
    # <2% steady-state drop within ~45 training steps at 8x2 experts,
    # ~18% faster than cf 2.0 (smaller expert queues = fewer gathered
    # bytes and smaller FFN batches).
    moe_capacity_factor: float = 1.5
    moe_aux_weight: float = 0.05   # load-balance loss weight in lm_loss
    # Router z-loss weight (ST-MoE): penalizes squared logsumexp of the
    # router logits so they don't drift large (which makes routing
    # saturate and bf16 logits overflow). 0 = off.
    moe_z_weight: float = 1e-3
    ep_axis: str | None = None
    # Positional encoding: "learned" (additive table, the default) or
    # "rope" (rotary: q/k rotated per position inside attention — relative
    # positions, no learned table, extrapolates past the training length).
    # Under sequence parallelism each shard rotates with its global offset.
    pos_embedding: str = "learned"
    rope_theta: float = 10000.0
    # Grouped-query attention: k/v get n_kv_heads heads (must divide
    # n_heads); queries keep n_heads. None = multi-head (k/v fused in
    # wqkv); 1 = multi-query. The KV cache shrinks by n_heads/n_kv_heads —
    # the long-context decode memory lever.
    n_kv_heads: int | None = None
    # Chunked cross-entropy head: compute logits + log-softmax in
    # loss_chunk-token slices under jax.checkpoint so [B, T, V] never
    # materializes (chunked_token_loss) — the long-context TRAINING memory
    # lever on the head side (the head, not attention, is the single-chip
    # HBM ceiling past ~32k tokens). 0 = dense head.
    loss_chunk: int = 0

    def __post_init__(self):
        if self.attn_window is not None and self.attn_window < 1:
            raise ValueError(
                f"attn_window must be >= 1, got {self.attn_window}")
        if self.loss_chunk < 0:
            raise ValueError(
                f"loss_chunk must be >= 0 (0 = dense head), got "
                f"{self.loss_chunk}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def gqa(self) -> bool:
        return self.n_kv_heads is not None

    @property
    def moe(self) -> "MoEConfig | None":
        if not self.moe_experts:
            return None
        from distributed_model_parallel_tpu.ops.moe import MoEConfig
        return MoEConfig(num_experts=self.moe_experts, d_model=self.d_model,
                         d_ff=self.d_ff, top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor)


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Parameter pytree; blocks stacked on a leading [n_layers] axis."""
    k = jax.random.split(rng, 8)
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dt) * (fan_in ** -0.5))

    def stack(key, shape, fan_in):
        return dense(key, (L,) + shape, fan_in)

    blocks = {
        "ln1_scale": jnp.ones((L, d), dt),
        "ln1_bias": jnp.zeros((L, d), dt),
        "wo": stack(k[3], (d, d), d),
        "ln2_scale": jnp.ones((L, d), dt),
        "ln2_bias": jnp.zeros((L, d), dt),
    }
    if cfg.gqa:
        if not (1 <= cfg.kv_heads <= cfg.n_heads):
            raise ValueError(f"n_kv_heads={cfg.kv_heads} must be in "
                             f"[1, n_heads={cfg.n_heads}]")
        if cfg.n_heads % cfg.kv_heads:
            raise ValueError(f"n_kv_heads={cfg.kv_heads} must divide "
                             f"n_heads={cfg.n_heads}")
        blocks["wq"] = stack(k[2], (d, cfg.n_heads, cfg.head_dim), d)
        blocks["wkv"] = stack(jax.random.fold_in(k[2], 1),
                              (d, cfg.kv_heads, 2 * cfg.head_dim), d)
    else:
        # [d, H, 3*Dh]: head dim explicit so tensor parallelism shards
        # whole heads (column-parallel over the H axis).
        blocks["wqkv"] = stack(k[2], (d, cfg.n_heads, 3 * cfg.head_dim), d)
    if cfg.moe_experts:
        E = cfg.moe_experts
        blocks.update({
            "router": stack(k[4], (d, E), d),
            "w_in": stack(k[5], (E, d, f), d),
            "w_out": stack(k[7], (E, f, d), f),
        })
    else:
        blocks.update({
            "w1": stack(k[4], (d, f), d),
            "b1": jnp.zeros((L, f), dt),
            "w2": stack(k[5], (f, d), f),
            "b2": jnp.zeros((L, d), dt),
        })
    out = {
        "embed": jax.random.normal(k[0], (cfg.vocab_size, d), dt) * 0.02,
        "blocks": blocks,
        "ln_f_scale": jnp.ones((d,), dt),
        "ln_f_bias": jnp.zeros((d,), dt),
        "head": dense(k[6], (d, cfg.vocab_size), d),
    }
    if cfg.pos_embedding == "learned":
        out["pos"] = jax.random.normal(k[1], (cfg.max_seq_len, d), dt) * 0.02
    elif cfg.pos_embedding != "rope":
        raise ValueError(f"unknown pos_embedding {cfg.pos_embedding!r}")
    return out


def layer_norm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding (GPT-NeoX half-split convention).

    x: [B, T, H, Dh] (Dh even), positions: [T] absolute token positions
    shared across the batch, or [B, T] per-row positions (the serving
    engine's continuous decode batch, where every row sits at its own
    offset). Rotates each (x[..., i], x[..., i + Dh/2]) pair by
    position * theta^(-2i/Dh); q·k then depends only on relative
    position, which is what makes the per-shard global offsets under
    sequence parallelism (and the per-step offsets in cached decoding)
    compose exactly with full attention.
    """
    dh = x.shape[-1]
    if dh % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {dh}")
    inv_freq = theta ** (-jnp.arange(0, dh, 2, dtype=jnp.float32) / dh)
    # [T, Dh/2] (shared) or [B, T, Dh/2] (per-row); the trailing [T, 1, F]
    # broadcast shape is the same either way.
    ang = positions.astype(jnp.float32)[..., :, None] * inv_freq
    cos = jnp.cos(ang)[..., :, None, :]
    sin = jnp.sin(ang)[..., :, None, :]
    if positions.ndim == 1:
        cos, sin = cos[None], sin[None]
    x1, x2 = x[..., :dh // 2], x[..., dh // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _rope_qk(q: jax.Array, k: jax.Array, cfg: TransformerConfig
             ) -> tuple[jax.Array, jax.Array]:
    """Rotate q/k for the training path. Inside a sequence-parallel
    shard_map each shard covers [i*T_local, (i+1)*T_local); outside, the
    (global) sequence starts at 0."""
    t = q.shape[1]
    start = (jax.lax.axis_index(cfg.sp_axis) * t
             if cfg.sp_axis is not None else 0)
    positions = start + jnp.arange(t)
    return (apply_rope(q, positions, cfg.rope_theta),
            apply_rope(k, positions, cfg.rope_theta))


def _qkv_proj(bp: dict, h: jax.Array, cfg: TransformerConfig):
    """Project to q [B,T,H(_local),Dh] and k/v [B,T,Hkv(_local),Dh] —
    fused wqkv for multi-head, separate wq/wkv for grouped-query. One
    helper for training, prefill, and cached decode so they never
    diverge."""
    if cfg.gqa:
        q = jnp.einsum("btd,dhx->bthx", h, bp["wq"])
        kv = jnp.einsum("btd,dhx->bthx", h, bp["wkv"])
        k, v = jnp.split(kv, 2, axis=-1)
    else:
        qkv = jnp.einsum("btd,dhx->bthx", h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
    return q, k, v


def _repeat_kv(x: jax.Array, q: jax.Array) -> jax.Array:
    """Broadcast kv heads up to the query head count ([..., Hkv, Dh] ->
    [..., H, Dh]). The group factor comes from *local* shapes so it is
    correct under tensor-parallel head sharding."""
    groups = q.shape[2] // x.shape[2]
    return x if groups == 1 else jnp.repeat(x, groups, axis=2)


def _attention(q, k, v, cfg: TransformerConfig):
    if cfg.sp_axis is not None:
        if cfg.attn_window is not None:
            raise ValueError(
                "attn_window is not supported with sequence parallelism")
        if cfg.sp_impl == "ring":
            return ring_attention(q, k, v, cfg.sp_axis, causal=True,
                                  impl=cfg.attn_impl)
        from distributed_model_parallel_tpu.ops.ring_attention import (
            ulysses_attention,
        )
        return ulysses_attention(q, k, v, cfg.sp_axis, causal=True,
                                 impl=cfg.attn_impl)
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        flash_attention,
        should_use_flash,
    )
    if cfg.attn_window is not None:
        # Banded compute lives in the flash kernels (both directions);
        # there is no windowed XLA fallback, so the knob forces flash.
        if cfg.attn_impl != "flash":
            raise ValueError(
                "attn_window requires attn_impl='flash' (the banded "
                "block-skipping lives in the pallas kernels)")
        return flash_attention(q, k, v, causal=True, window=cfg.attn_window)
    if should_use_flash(q.shape[1], causal=True, impl=cfg.attn_impl,
                        head_dim=q.shape[-1], dtype=q.dtype):
        return flash_attention(q, k, v, causal=True)
    return full_attention(q, k, v, causal=True)


def block_apply(bp: dict, x: jax.Array, cfg: TransformerConfig
                ) -> tuple[jax.Array, jax.Array]:
    """One transformer block on [B, T(_local), d]. ``bp`` holds *unstacked*
    per-layer arrays (a leaf slice of params["blocks"]). Returns
    ``(x, aux)`` where ``aux`` is the MoE load-balance loss (0 for dense).

    Tensor parallelism: when ``cfg.tp_axis`` is bound, wqkv/w1 arrive
    column-sharded and wo/w2 row-sharded (shard_map hands each device its
    slice); the two psums below complete the Megatron pattern.
    """
    b, t, d = x.shape

    h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    q, k, v = _qkv_proj(bp, h, cfg)          # q:[B,T,H,Dh] kv:[B,T,Hkv,Dh]
    if cfg.pos_embedding == "rope":
        q, k = _rope_qk(q, k, cfg)
    k, v = _repeat_kv(k, q), _repeat_kv(v, q)
    o = _attention(q, k, v, cfg)             # [B,T,H_local,Dh]
    o = o.reshape(b, t, -1) @ bp["wo"]       # row-parallel: partial sums
    if cfg.tp_axis is not None:
        o = jax.lax.psum(o, cfg.tp_axis)
    x = x + o

    h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h, aux = _ffn(bp, h, cfg, tp_axis=cfg.tp_axis, ep_axis=cfg.ep_axis)
    return x + h, aux


def _ffn(bp: dict, h: jax.Array, cfg: TransformerConfig, *,
         tp_axis: str | None, ep_axis: str | None):
    """Post-attention MLP tail, shared by the training path (``block_apply``)
    and cached decoding (``_decode_block``) so they cannot diverge.
    Returns (y, aux)."""
    if cfg.moe_experts:
        from distributed_model_parallel_tpu.ops.moe import moe_ffn
        y, aux = moe_ffn(
            {"router": bp["router"], "w_in": bp["w_in"],
             "w_out": bp["w_out"]},
            h, cfg.moe, ep_axis=ep_axis)
        return y, aux.astype(jnp.float32)
    y = jax.nn.gelu(h @ bp["w1"] + bp["b1"])
    y = y @ bp["w2"]
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    y = y + bp["b2"]                         # bias added once, post-psum
    return y, jnp.zeros((AUX_STATS,), jnp.float32)


def blocks_scan(blocks: dict, x: jax.Array, cfg: TransformerConfig
                ) -> tuple[jax.Array, jax.Array]:
    """Run all stacked blocks with lax.scan (single device / per-stage).
    Returns ``(x, aux)``; aux is the mean per-layer MoE load-balance loss."""
    apply = block_apply
    if cfg.remat:
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "full":
            policy = None
        else:
            raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}; "
                             f"known: full, dots")
        apply = jax.checkpoint(block_apply, static_argnums=(2,),
                               policy=policy)

    def body(carry, bp):
        carry, aux = apply(bp, carry, cfg)
        return carry, aux

    out, auxes = jax.lax.scan(body, x, blocks)
    return out, jnp.mean(auxes, axis=0)       # [AUX_STATS], mean over layers


def embed(params: dict, tokens: jax.Array, cfg: TransformerConfig,
          *, pos_offset: int = 0) -> jax.Array:
    if cfg.pos_embedding == "rope":
        # Positions enter through q/k rotation in attention, not the embed.
        # The rotation path (_rope_qk) counts from 0 (or the shard's global
        # offset), so an embed-level offset cannot be honored — reject it
        # loudly rather than return silently mis-rotated logits. Cached
        # decoding handles its own offsets (generate/forward_one).
        if pos_offset:
            raise ValueError(
                "pos_offset is not supported with pos_embedding='rope'; "
                "use generate() for offset (cached) decoding")
        return params["embed"][tokens]
    t = tokens.shape[1]
    pos = jax.lax.dynamic_slice_in_dim(params["pos"], pos_offset, t)
    return params["embed"][tokens] + pos[None]


def unembed(params: dict, x: jax.Array) -> jax.Array:
    x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    return x @ params["head"]


def hidden_with_aux(params: dict, tokens: jax.Array, cfg: TransformerConfig,
                    *, pos_offset: int = 0) -> tuple[jax.Array, jax.Array]:
    """Forward up to the final hidden states: [B, T] int tokens ->
    ([B, T, d] pre-head activations, moe aux loss). Shared by the dense
    head (``apply_with_aux``) and the chunked head (``lm_loss`` with
    ``loss_chunk``) so the two paths cannot drift."""
    x = embed(params, tokens, cfg, pos_offset=pos_offset)
    return blocks_scan(params["blocks"], x, cfg)


def apply_with_aux(params: dict, tokens: jax.Array, cfg: TransformerConfig,
                   *, pos_offset: int = 0) -> tuple[jax.Array, jax.Array]:
    """Full forward: [B, T] int tokens -> ([B, T, V] logits, moe aux loss)."""
    x, aux = hidden_with_aux(params, tokens, cfg, pos_offset=pos_offset)
    return unembed(params, x), aux


def apply(params: dict, tokens: jax.Array, cfg: TransformerConfig,
          *, pos_offset: int = 0) -> jax.Array:
    """Full forward: [B, T] int tokens -> [B, T, V] logits."""
    return apply_with_aux(params, tokens, cfg, pos_offset=pos_offset)[0]


def aux_loss(aux: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Weighted scalar loss contribution of the [AUX_STATS] stats vector:
    balance and z are loss terms with their own weights; drop rate is a
    metric only (zero-gradient by construction)."""
    return (cfg.moe_aux_weight * aux[0]
            + cfg.moe_z_weight * aux[1])


def token_loss(logits: jax.Array, targets: jax.Array, aux: jax.Array,
               cfg: TransformerConfig) -> jax.Array:
    """Mean next-token cross-entropy + weighted MoE auxiliary losses.
    The single shared loss for the single-device and SPMD-pipeline paths
    (their parity is what tests compare)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + aux_loss(aux, cfg)


def chunked_nll_sum(params: dict, x: jax.Array, targets: jax.Array,
                    chunk: int) -> jax.Array:
    """SUM of next-token NLL over ``unembed(x)`` without ever materializing
    the ``[B, T, V]`` logits tensor.

    At long context the single-chip HBM ceiling is the vocabulary head,
    not attention: seq-64k x 32k-vocab logits are 4.3 GB bf16 plus f32
    softmax temporaries (measured: the seq-64k train step wants 20.7 GB
    on a 15.8 GB v5e with the dense head; flash attention itself is
    O(T)). This scans the sequence in ``chunk``-token slices, computing
    each slice's logits + log-softmax inside a ``jax.checkpoint`` region
    so the backward rematerializes them per chunk: peak memory drops to
    O(B * chunk * V) for one extra head forward of recompute (the same
    FLOPs-for-HBM trade the block remat makes; the fused-linear-CE trick,
    expressed as scan + remat instead of a custom kernel).

    Sum units so callers pick their own normalization: the dense-head-
    equivalent mean loss (``chunked_token_loss``) and the SPMD 1F1B head
    (``parallel/spmd_pipeline``, which accumulates sums across microbatches
    and shards) share this one definition."""
    b, t, d = x.shape
    if t % chunk:
        raise ValueError(f"seq len {t} not divisible by loss_chunk={chunk}")
    n = t // chunk
    xs = x.reshape(b, n, chunk, d).swapaxes(0, 1)        # [n, B, c, D]
    ts = targets.reshape(b, n, chunk).swapaxes(0, 1)     # [n, B, c]

    @jax.checkpoint
    def body(carry, xt):
        xc, tc = xt
        logp = jax.nn.log_softmax(unembed(params, xc).astype(jnp.float32),
                                  axis=-1)
        nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        return carry + nll.sum(), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
    return total


def chunked_token_loss(params: dict, x: jax.Array, targets: jax.Array,
                       aux: jax.Array, cfg: TransformerConfig,
                       chunk: int) -> jax.Array:
    """``token_loss`` over ``unembed(x)`` via ``chunked_nll_sum`` — the
    [B, T, V] logits never materialize (see that docstring)."""
    b, t, _ = x.shape
    return (chunked_nll_sum(params, x, targets, chunk) / (b * t)
            + aux_loss(aux, cfg))


def lm_loss(params: dict, tokens: jax.Array, targets: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    """Mean next-token cross-entropy (+ weighted MoE load-balance loss)."""
    if cfg.loss_chunk:
        x, aux = hidden_with_aux(params, tokens, cfg)
        return chunked_token_loss(params, x, targets, aux, cfg,
                                  cfg.loss_chunk)
    logits, aux = apply_with_aux(params, tokens, cfg)
    return token_loss(logits, targets, aux, cfg)


def _cached_block(bp: dict, ck: jax.Array, cv: jax.Array, layer: jax.Array,
                  x: jax.Array, positions: jax.Array,
                  cfg: TransformerConfig, *,
                  tp_axis: str | None = None, read_len: int | None = None):
    """One block for C contiguous token positions with a STACKED KV cache.

    x: [B, C, d]; positions: [C] absolute positions (contiguous);
    ck/cv: [L, B, T_total, Hkv, Dh] — ALL layers' caches (kv heads only,
    the GQA memory win; Hkv is the LOCAL head count under tensor
    parallelism); ``layer`` (traced scalar) selects this block's slab.
    Returns (x, ck, cv) with the [layer, :, positions] slab updated.

    The whole stack stays in the enclosing scan's CARRY and this function
    writes one [B, C, Hkv, Dh] slab — so XLA updates the cache buffer in
    place across layers and steps. The pre-round-5 layout (per-layer
    caches as scan xs with stacked ys outputs) forced a full-cache
    materialization every decode step: ~25% of decode device time was
    whole-cache copies (hardware trace, VERDICT r4 weak #3).

    ``read_len`` (static) scores against only the first ``read_len``
    cache positions instead of the whole padding — callers guarantee
    every attended position is below it (``generate`` decodes in
    read-boundary segments); the masked unwritten tail was pure wasted
    HBM reads. Masking stays position-index based, so shapes are static
    under scan. C=1 is the decode step; C=chunk is chunked prefill
    (scores peak at O(C * read_len) instead of O(T0^2)).

    ``tp_axis`` enables the Megatron psums (wo and the dense FFN) when the
    block runs inside a shard_map with head-sharded weights — the decode
    counterpart of ``block_apply``'s training-path psums.
    """
    b, c = x.shape[:2]
    total = ck.shape[2]

    h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    q, k, v = _qkv_proj(bp, h, cfg)      # q:[B,C,H,Dh] kv:[B,C,Hkv,Dh]
    if cfg.pos_embedding == "rope":
        # The cache holds *rotated* keys (prefill rotates too), so one
        # rotation at insert time makes scores relative-position correct.
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype)[None],
                                      (layer, 0, positions[0], 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype)[None],
                                      (layer, 0, positions[0], 0, 0))
    rl = total if read_len is None else min(read_len, total)
    # This layer's written prefix (reads AFTER the write above, so the
    # current positions' keys are included in the scores).
    kr = jax.lax.dynamic_slice(
        ck, (layer, 0, 0, 0, 0), (1, *ck.shape[1:]))[0, :, :rl]
    vr = jax.lax.dynamic_slice(
        cv, (layer, 0, 0, 0, 0), (1, *cv.shape[1:]))[0, :, :rl]
    # Grouped scores: query head h attends kv head h // G (G=1 for MHA),
    # matching _repeat_kv's head mapping in the training path.
    hkv = ck.shape[3]
    qg = q.reshape(b, c, hkv, q.shape[2] // hkv, cfg.head_dim)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kr) * (cfg.head_dim ** -0.5)
    # Same (pos - W, pos] band predicate as the training kernels
    # (ops/pallas_attention.band_keep; pure causal when attn_window=None) —
    # it also masks the cache's not-yet-written tail (key pos > query pos).
    from distributed_model_parallel_tpu.ops.pallas_attention import band_keep

    keep = band_keep(positions[:, None], jnp.arange(rl)[None, :],
                     cfg.attn_window)                  # [C, rl]
    s = jnp.where(keep[None, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, vr)         # [B,C,Hkv,G,Dh]
    o = o.reshape(b, c, -1) @ bp["wo"]
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    x = x + o

    h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h, _ = _ffn(bp, h, cfg, tp_axis=tp_axis, ep_axis=None)
    return x + h, ck, cv


# Decode read-boundary segment size: each segment's scan reads the cache
# prefix up to the next multiple of this. Shared with bench.py's decode
# byte model — tune here and the published roofline stays honest.
DECODE_READ_SEG = 256


def _filter_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits to -inf (k static; [B, V])."""
    kth = jax.lax.top_k(logits, k)[0][:, -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering: keep the smallest set of tokens whose cumulative
    probability reaches p (always at least the top token). Static-shape:
    argsort, exclusive cumulative softmax mass, scatter the per-rank keep
    mask back through the sort permutation — a value threshold would also
    keep any token whose logit *ties* the last-kept one, letting duplicate
    logits outside the nucleus leak into the sampling set."""
    b, v = logits.shape
    order = jnp.argsort(logits, axis=-1)[:, ::-1]        # descending ranks
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # Exclusive cumsum: a token is kept if the mass *before* it is < p.
    keep_sorted = (jnp.cumsum(probs, axis=-1) - probs) < p
    keep = jnp.zeros((b, v), bool).at[
        jnp.arange(b)[:, None], order].set(keep_sorted)
    return jnp.where(keep, logits, -jnp.inf)


def validate_sampling(cfg: TransformerConfig, temperature: float,
                      top_k: int | None, top_p: float | None) -> None:
    """The one set of sampling-knob rules ``generate`` and the serving
    engine (serve/engine.py) both enforce."""
    if (top_k is not None or top_p is not None) and temperature <= 0:
        raise ValueError("top_k/top_p filter the sampling distribution; "
                         "set temperature > 0 (greedy ignores them)")
    if top_k is not None and not (1 <= top_k <= cfg.vocab_size):
        raise ValueError(f"top_k must be in [1, {cfg.vocab_size}], got {top_k}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def make_sampler(cfg: TransformerConfig, temperature: float,
                 top_k: int | None, top_p: float | None):
    """``sample(logits [B, V], key) -> [B] int32``: greedy argmax at
    temperature 0, else temperature/top-k/nucleus sampling — the single
    token-selection definition ``generate`` and the serving engine share
    (one ``key`` drives the whole batch; per-row-keyed callers vmap it)."""
    validate_sampling(cfg, temperature, top_k, top_p)

    def sample(logits, sub):
        if temperature > 0:
            logits = logits / temperature
            if top_k is not None:
                logits = _filter_top_k(logits, top_k)
            if top_p is not None:
                logits = _filter_top_p(logits, top_p)
            return jax.random.categorical(sub, logits).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return sample


def generate(params: dict, cfg: TransformerConfig, prompt: jax.Array,
             steps: int, *, rng: jax.Array | None = None,
             temperature: float = 0.0, top_k: int | None = None,
             top_p: float | None = None, tp_axis: str | None = None,
             prefill_chunk: int | None = None) -> jax.Array:
    """Autoregressive decoding with a per-layer KV cache.

    prompt: [B, T0] int32 -> [B, T0 + steps]. Greedy when temperature == 0,
    else softmax sampling at the given temperature, optionally filtered by
    ``top_k`` (keep the k best tokens) and/or ``top_p`` (nucleus: smallest
    set reaching cumulative probability p) — both static-shape jittable.
    The whole decode is jittable: one ``lax.scan`` per 256-position
    read-boundary segment (DECODE_READ_SEG; each segment's step reads
    only the block-quantized written cache prefix — static shapes, cache
    updated in place via dynamic_update_slice), the TPU-native
    replacement for a Python token-by-token loop. Long generations
    compile one small scan per segment.

    ``tp_axis`` runs the cached blocks tensor-parallel: call inside a
    shard_map whose block weights are head-sharded over that axis (the
    training layout — ``generate_sharded`` wraps this) and the KV cache
    holds only the local heads while wo/FFN psums complete each block.
    ``prefill_chunk`` processes the prompt in C-token slices against the
    growing cache instead of one [T0, T0]-score batched forward: same
    FLOPs, peak attention memory O(C * T_total) — the long-prompt lever.

    The reference has no inference path at all; this rounds out the LM
    tooling the flagship model needs.
    """
    b, t0 = prompt.shape
    total = t0 + steps
    if steps < 1:
        raise ValueError("steps must be >= 1")
    if total > cfg.max_seq_len:
        raise ValueError(f"prompt + steps = {total} exceeds max_seq_len "
                         f"{cfg.max_seq_len}")
    if prefill_chunk is not None:
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        if t0 % prefill_chunk:
            raise ValueError(f"prompt length {t0} not divisible by "
                             f"prefill_chunk={prefill_chunk}")
    if rng is None:
        rng = jax.random.key(0)
    sample = make_sampler(cfg, temperature, top_k, top_p)

    rng, sub = jax.random.split(rng)
    if prefill_chunk is not None:
        # -- Chunked prefill: run each C-token slice of the prompt through
        # every layer's cached block (intra-slice causality and the band
        # come from the shared position mask), writing the cache as it
        # goes. The batched path's [T0, T0] score tensor never exists.
        hkv = (params["blocks"]["wkv"].shape[2] if cfg.gqa
               else params["blocks"]["wqkv"].shape[2])   # LOCAL kv heads
        cache_k = jnp.zeros((cfg.n_layers, b, total, hkv, cfg.head_dim),
                            cfg.dtype)
        cache_v = jnp.zeros_like(cache_k)
        n_chunks = t0 // prefill_chunk
        toks_c = prompt.reshape(b, n_chunks, prefill_chunk).swapaxes(0, 1)

        def chunk_step(carry, xs):
            cache_k, cache_v = carry
            toks, j = xs
            positions = j * prefill_chunk + jnp.arange(prefill_chunk)
            x = params["embed"][toks]
            if cfg.pos_embedding == "learned":
                x = x + jax.lax.dynamic_slice_in_dim(
                    params["pos"], j * prefill_chunk, prefill_chunk)[None]

            def layer(carry2, xs2):
                x, ck, cv = carry2
                bp, li = xs2
                x, ck, cv = _cached_block(bp, ck, cv, li, x, positions,
                                          cfg, tp_axis=tp_axis)
                return (x, ck, cv), None

            (x, cache_k, cache_v), _ = jax.lax.scan(
                layer, (x, cache_k, cache_v),
                (params["blocks"], jnp.arange(cfg.n_layers)))
            return (cache_k, cache_v), unembed(params, x[:, -1:])[:, 0]

        (cache_k, cache_v), chunk_logits = jax.lax.scan(
            chunk_step, (cache_k, cache_v),
            (toks_c, jnp.arange(n_chunks)))
        tok0 = sample(chunk_logits[-1], sub)     # token at position t0
    else:
        # -- Batched prefill: one forward over the whole prompt fills every
        # layer's KV cache at once (O(1) forwards, not O(t0) steps).
        x = embed(params, prompt, cfg)

        def prefill_layer(x, bp):
            h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
            q, k, v = _qkv_proj(bp, h, cfg)    # kv carry Hkv heads
            if cfg.pos_embedding == "rope":
                positions = jnp.arange(t0)
                q = apply_rope(q, positions, cfg.rope_theta)
                k = apply_rope(k, positions, cfg.rope_theta)
            # Cache Hkv-head k/v; attention runs on broadcast heads.
            kr, vr = _repeat_kv(k, q), _repeat_kv(v, q)
            if cfg.attn_window is None:
                o = full_attention(q, kr, vr, causal=True)
            else:
                # Banded prefill: the shared band predicate keeps this,
                # the cached decode, and the training kernels on one
                # definition. Prompts are short, so the explicit mask is
                # fine here.
                from distributed_model_parallel_tpu.ops.pallas_attention import (
                    band_keep,
                )

                s = (jnp.einsum("bqhd,bkhd->bhqk", q, kr)
                     * (cfg.head_dim ** -0.5))
                posa = jnp.arange(t0)
                keep = band_keep(posa[:, None], posa[None, :],
                                 cfg.attn_window)
                s = jnp.where(keep[None, None], s, -jnp.inf)
                o = jnp.einsum("bhqk,bkhd->bqhd",
                               jax.nn.softmax(s, axis=-1).astype(q.dtype),
                               vr)
            o = o.reshape(b, t0, -1) @ bp["wo"]
            if tp_axis is not None:
                o = jax.lax.psum(o, tp_axis)
            x = x + o
            h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
            h, _ = _ffn(bp, h, cfg, tp_axis=tp_axis, ep_axis=None)
            return x + h, (k.astype(cfg.dtype), v.astype(cfg.dtype))

        x, (ks, vs) = jax.lax.scan(prefill_layer, x, params["blocks"])
        pad = [(0, 0), (0, 0), (0, total - t0), (0, 0), (0, 0)]
        cache_k = jnp.pad(ks, pad)               # [L, B, total, Hkv, Dh]
        cache_v = jnp.pad(vs, pad)
        tok0 = sample(unembed(params, x)[:, -1], sub)  # token at position t0

    # -- Decode: one cached step per new position.
    def forward_one(cache_k, cache_v, tok, pos, read_len):
        x = params["embed"][tok][:, None, :]
        if cfg.pos_embedding == "learned":
            x = x + jax.lax.dynamic_slice_in_dim(params["pos"], pos, 1)[None]

        def layer(carry, xs):
            x, ck, cv = carry
            bp, li = xs
            x, ck, cv = _cached_block(bp, ck, cv, li, x,
                                      jnp.reshape(pos, (1,)), cfg,
                                      tp_axis=tp_axis, read_len=read_len)
            return (x, ck, cv), None

        (x, cache_k, cache_v), _ = jax.lax.scan(
            layer, (x, cache_k, cache_v),
            (params["blocks"], jnp.arange(cfg.n_layers)))
        return unembed(params, x)[:, 0], cache_k, cache_v   # [B, V]

    def make_body(read_len):
        def body(carry, pos):
            cache_k, cache_v, tok, rng = carry
            logits, cache_k, cache_v = forward_one(cache_k, cache_v, tok,
                                                   pos, read_len)
            rng, sub = jax.random.split(rng)
            tok_next = sample(logits, sub)
            return (cache_k, cache_v, tok_next, rng), tok_next
        return body

    # Positions t0 .. total-2 consume tokens t0 .. total-2 and emit
    # tokens t0+1 .. total-1 (steps-1 of them; tok0 is already emitted).
    # Decoding runs in READ-BOUNDARY SEGMENTS: position p only attends
    # keys 0..p, so a scan whose positions all sit below a static boundary
    # reads just that cache prefix — the written part plus <SEG slack —
    # instead of the full padded [total] every step. Decode is HBM-bound
    # on exactly that read; the masked-out tail was pure wasted bandwidth
    # (VERDICT r4 weak #3). Each boundary compiles its own small scan.
    SEG = DECODE_READ_SEG
    parts = []
    carry = (cache_k, cache_v, tok0, rng)
    p = t0
    while p < total - 1:
        hi = min(total, (p // SEG + 1) * SEG)
        p_end = min(total - 1, hi)          # positions p..p_end-1 read <=hi
        carry, toks_seg = jax.lax.scan(
            make_body(hi), carry, jnp.arange(p, p_end))
        parts.append(toks_seg)
        p = p_end
    toks = jnp.concatenate(parts, axis=0) if parts else \
        jnp.zeros((0, b), jnp.int32)
    return jnp.concatenate([prompt, tok0[:, None], toks.T], axis=1)


def generate_sharded(params: dict, cfg: TransformerConfig, prompt: jax.Array,
                     steps: int, spec, *, rng: jax.Array | None = None,
                     temperature: float = 0.0, top_k: int | None = None,
                     top_p: float | None = None,
                     prefill_chunk: int | None = None) -> jax.Array:
    """``generate`` under a device mesh: batch over ``data``, heads over
    ``model`` (tensor-parallel KV cache — each device caches only its local
    kv heads; wo/FFN psums complete each block, exactly the training
    layout from ``parallel/tensor_parallel.block_specs``).

    Greedy decoding is token-identical to replicated ``generate``
    (tests/test_generate_sharded.py). Sampled decoding folds the data-shard
    index into the key (ADVICE r4: a replicated key would draw identical
    noise on every shard — correlated samples across the batch), so under a
    sharded batch the streams are independent but differ from the
    replicated run's per-row split; the psum'd logits themselves are
    bit-identical across the model axis.

    A model trained tp-sharded no longer has to be gathered onto one
    device to decode (the r3 gap: a 256k-token model the framework could
    train but not serve sharded).
    """
    from jax.sharding import NamedSharding

    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        kv_heads_shardable,
        param_specs,
    )

    if cfg.moe_experts and cfg.ep_axis:
        raise ValueError("expert-parallel decode is not implemented; "
                         "decode with experts replicated (ep_axis=None)")
    # Decode ignores the pipeline axis: blocks stay layer-stacked on every
    # device (stage_axis=None), sharded over model only.
    pspecs = param_specs(None, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=None,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    params = jax.tree.map(
        lambda x, ps: jax.device_put(x, NamedSharding(spec.mesh, ps)),
        params, pspecs, is_leaf=lambda x: isinstance(x, P))
    if rng is None:
        rng = jax.random.key(0)

    # Static: fold only when >1 shard exists — fold_in(rng, 0) != rng, so
    # a size-1 axis would needlessly diverge from replicated sampling.
    fold_data = (spec.data_axis is not None
                 and spec.mesh.shape[spec.data_axis] > 1)

    def body(params, prompt, rng):
        # Each data shard must sample an independent stream: the rng enters
        # replicated (in_specs P()), so without folding in the shard index
        # every shard would draw IDENTICAL noise for its (different) rows —
        # correlated samples across the batch at temperature > 0.
        if fold_data:
            rng = jax.random.fold_in(
                rng, jax.lax.axis_index(spec.data_axis))
        return generate(params, cfg, prompt, steps, rng=rng,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        tp_axis=cfg.tp_axis, prefill_chunk=prefill_chunk)

    fn = jax.shard_map(
        body, mesh=spec.mesh,
        in_specs=(pspecs, P(spec.data_axis), P()),
        out_specs=P(spec.data_axis),
        check_vma=False)
    return fn(params, prompt, rng)


def build_transformer(model_config) -> "TransformerConfig":
    """Registry adapter: ModelConfig.extra carries TransformerConfig fields."""
    extra = dict(model_config.extra)
    extra.setdefault("vocab_size", max(model_config.num_classes, 32))
    return TransformerConfig(**extra)
