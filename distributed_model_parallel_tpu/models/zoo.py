"""Extended CIFAR model zoo: the reference's full model menu.

The reference's DP driver carries a commented-out menu of 15 architectures
(``data_parallel.py:58-73``): VGG, ResNet, PreActResNet, GoogLeNet, DenseNet,
ResNeXt, MobileNet(v1), MobileNetV2, DPN, ShuffleNet(G2), SENet, ShuffleNetV2,
EfficientNet-B0, RegNetX-200MF, SimpleDLA. MobileNetV2 and ResNet live in
their own modules; this module provides the rest, each expressed as a staged
unit sequence (``models/staged.py``) so every zoo member works under every
parallelism strategy (DP/DDP/pipeline) unchanged.

All models are CIFAR-adapted (stride-1 3x3 stems, no stem max-pool) in the
same convention the reference uses for MobileNetV2
(``model/mobilenetv2.py:42,51,72``), NHWC layout, and share the three
BatchNorm modes (local / sync / none) from ``models/layers.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.layers import (
    ClassifierHead,
    ConvUnit,
    _norm,
)
from distributed_model_parallel_tpu.models.staged import StagedModel


class _ZooModule(nn.Module):
    """Shared hyperparameter plumbing for zoo blocks."""

    bn_mode: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: Any = jnp.float32
    axis_name: str | None = None

    @property
    def use_bias(self) -> bool:
        return self.bn_mode == "none"

    def norm(self, name: str):
        return _norm(self.bn_mode, momentum=self.bn_momentum,
                     epsilon=self.bn_epsilon, dtype=self.dtype,
                     axis_name=self.axis_name, name=name)

    def conv(self, features: int, kernel: int = 3, stride: int = 1,
             groups: int = 1, name: str = "conv"):
        return nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                       padding="SAME", feature_group_count=groups,
                       use_bias=self.use_bias, dtype=self.dtype, name=name)

    def cbr(self, x, features: int, *, train: bool, kernel: int = 3,
            stride: int = 1, groups: int = 1, act: bool = True,
            name: str = "conv"):
        """conv → norm → (relu)."""
        x = self.conv(features, kernel, stride, groups, name=name)(x)
        x = self.norm(f"{name}_bn")(x, train)
        return nn.relu(x) if act else x


_HPARAM_FIELDS = ("bn_mode", "bn_momentum", "bn_epsilon", "dtype", "axis_name")
_HPARAM_DEFAULTS = {f.name: f.default for f in dataclasses.fields(_ZooModule)
                    if f.name in _HPARAM_FIELDS}


def _common(kw: dict) -> dict:
    return {k: kw.get(k, _HPARAM_DEFAULTS[k]) for k in _HPARAM_FIELDS}


def _channel_shuffle(x, groups: int):
    """(N,H,W,C) channel shuffle across ``groups``."""
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


# ---------------------------------------------------------------------------
# VGG
# ---------------------------------------------------------------------------

VGG_CFG = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"),
    "vgg19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
              512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGGUnit(_ZooModule):
    """One 3x3 conv-BN-ReLU, optionally followed by a 2x2 max-pool."""

    features: int = 64
    pool: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool):
        x = self.cbr(x, self.features, train=train)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


def build_vgg(arch: str = "vgg16", num_classes: int = 10, **kw) -> StagedModel:
    cfg = VGG_CFG[arch]
    units: list[nn.Module] = []
    i = 0
    while i < len(cfg):
        feats = cfg[i]
        pool = i + 1 < len(cfg) and cfg[i + 1] == "M"
        units.append(VGGUnit(features=feats, pool=pool, **_common(kw)))
        i += 2 if pool else 1
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name=arch)


# ---------------------------------------------------------------------------
# PreActResNet / SENet
# ---------------------------------------------------------------------------


class PreActBlock(_ZooModule):
    """Pre-activation residual block (BN→ReLU→conv ×2), optional SE gate.

    ``se_ratio > 0`` turns this into the SENet-18 block: a squeeze-excite
    recalibration on the residual branch before the add.
    """

    features: int = 64
    stride: int = 1
    se_ratio: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool):
        pre = nn.relu(self.norm("pre_bn")(x, train))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = self.conv(self.features, 1, self.stride,
                                 name="shortcut")(pre)
        y = self.conv(self.features, 3, self.stride, name="conv0")(pre)
        y = nn.relu(self.norm("bn0")(y, train))
        y = self.conv(self.features, 3, 1, name="conv1")(y)
        if self.se_ratio > 0:
            squeezed = max(1, int(self.features * self.se_ratio))
            w = jnp.mean(y, axis=(1, 2), keepdims=True)
            w = nn.Conv(squeezed, (1, 1), dtype=self.dtype, name="se_fc0")(w)
            w = nn.relu(w)
            w = nn.Conv(self.features, (1, 1), dtype=self.dtype,
                        name="se_fc1")(w)
            y = y * nn.sigmoid(w)
        return y + shortcut


def _build_preact(name: str, num_classes: int, se_ratio: float,
                  **kw) -> StagedModel:
    # Bare conv stem: the first block's pre-activation BN normalizes it.
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 64, "kernel": 3, "stride": 1,
                       "act": False, "norm": False},), **_common(kw))
    ]
    for g, (feats, blocks) in enumerate(
            zip((64, 128, 256, 512), (2, 2, 2, 2))):
        for b in range(blocks):
            units.append(PreActBlock(
                features=feats, stride=(2 if g > 0 and b == 0 else 1),
                se_ratio=se_ratio, **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name=name)


def build_preact_resnet18(num_classes: int = 10, **kw) -> StagedModel:
    return _build_preact("preactresnet18", num_classes, 0.0, **kw)


def build_senet18(num_classes: int = 10, **kw) -> StagedModel:
    """SENet-18: PreAct blocks with squeeze-excite (ratio 1/16)."""
    return _build_preact("senet18", num_classes, 1.0 / 16.0, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet
# ---------------------------------------------------------------------------

# (n1x1, n3x3red, n3x3, n5x5red, n5x5, pool_planes), pre-pool flag
GOOGLE_CFG = (
    ((64, 96, 128, 16, 32, 32), False),
    ((128, 128, 192, 32, 96, 64), True),     # max-pool after b3
    ((192, 96, 208, 16, 48, 64), False),
    ((160, 112, 224, 24, 64, 64), False),
    ((128, 128, 256, 24, 64, 64), False),
    ((112, 144, 288, 32, 64, 64), False),
    ((256, 160, 320, 32, 128, 128), True),   # max-pool after e4
    ((256, 160, 320, 32, 128, 128), False),
    ((384, 192, 384, 48, 128, 128), False),
)


class Inception(_ZooModule):
    """Four-branch inception module; 5x5 realized as two 3x3 convs."""

    spec: tuple = (64, 96, 128, 16, 32, 32)
    pool_after: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool):
        n1, n3r, n3, n5r, n5, npool = self.spec
        b1 = self.cbr(x, n1, train=train, kernel=1, name="b1")
        b2 = self.cbr(x, n3r, train=train, kernel=1, name="b2a")
        b2 = self.cbr(b2, n3, train=train, kernel=3, name="b2b")
        b3 = self.cbr(x, n5r, train=train, kernel=1, name="b3a")
        b3 = self.cbr(b3, n5, train=train, kernel=3, name="b3b")
        b3 = self.cbr(b3, n5, train=train, kernel=3, name="b3c")
        b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = self.cbr(b4, npool, train=train, kernel=1, name="b4")
        y = jnp.concatenate([b1, b2, b3, b4], axis=-1)
        if self.pool_after:
            y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        return y


def build_googlenet(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 192, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for spec, pool_after in GOOGLE_CFG:
        units.append(Inception(spec=spec, pool_after=pool_after, **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="googlenet")


# ---------------------------------------------------------------------------
# DenseNet-121
# ---------------------------------------------------------------------------


class DenseBlock(_ZooModule):
    """``num_layers`` bottleneck layers (BN→ReLU→1x1→BN→ReLU→3x3, concat),
    optionally followed by a transition (BN→1x1 compress→avg-pool 2)."""

    num_layers: int = 6
    growth: int = 32
    transition: bool = True
    reduction: float = 0.5

    @nn.compact
    def __call__(self, x, *, train: bool):
        for i in range(self.num_layers):
            y = nn.relu(self.norm(f"l{i}_bn0")(x, train))
            y = self.conv(4 * self.growth, 1, name=f"l{i}_conv0")(y)
            y = nn.relu(self.norm(f"l{i}_bn1")(y, train))
            y = self.conv(self.growth, 3, name=f"l{i}_conv1")(y)
            x = jnp.concatenate([x, y], axis=-1)
        if self.transition:
            x = nn.relu(self.norm("t_bn")(x, train))
            x = self.conv(int(x.shape[-1] * self.reduction), 1, name="t_conv")(x)
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        return x


class DenseHead(_ZooModule):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, *, train: bool):
        x = nn.relu(self.norm("bn")(x, train))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="linear")(x)


def build_densenet121(num_classes: int = 10, **kw) -> StagedModel:
    growth = 32
    # Bare conv stem: the first dense layer's BN normalizes it.
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 2 * growth, "kernel": 3, "stride": 1,
                       "act": False, "norm": False},), **_common(kw))
    ]
    for i, num_layers in enumerate((6, 12, 24, 16)):
        units.append(DenseBlock(num_layers=num_layers, growth=growth,
                                transition=(i < 3), **_common(kw)))
    units.append(DenseHead(num_classes=num_classes, **_common(kw)))
    return StagedModel(units=tuple(units), name="densenet121")


# ---------------------------------------------------------------------------
# ResNeXt-29 (2x64d)
# ---------------------------------------------------------------------------


class ResNeXtBlock(_ZooModule):
    """1x1 → grouped 3x3 → 1x1 (expansion 2) with projected shortcut."""

    cardinality: int = 2
    width: int = 64
    stride: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        group_width = self.cardinality * self.width
        out_features = 2 * group_width
        y = self.cbr(x, group_width, train=train, kernel=1, name="conv0")
        y = self.cbr(y, group_width, train=train, kernel=3,
                     stride=self.stride, groups=self.cardinality, name="conv1")
        y = self.cbr(y, out_features, train=train, kernel=1, act=False,
                     name="conv2")
        if self.stride != 1 or x.shape[-1] != out_features:
            x = self.conv(out_features, 1, self.stride, name="shortcut")(x)
            x = self.norm("shortcut_bn")(x, train)
        return nn.relu(y + x)


def build_resnext29_2x64d(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 64, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    width = 64
    for g in range(3):
        for b in range(3):
            units.append(ResNeXtBlock(
                cardinality=2, width=width,
                stride=(2 if g > 0 and b == 0 else 1), **_common(kw)))
        width *= 2
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="resnext29_2x64d")


# ---------------------------------------------------------------------------
# MobileNet (v1)
# ---------------------------------------------------------------------------

MOBILENET_CFG = (64, (128, 2), 128, (256, 2), 256, (512, 2),
                 512, 512, 512, 512, 512, (1024, 2), 1024)


class DepthwiseSeparable(_ZooModule):
    """Depthwise 3x3 → pointwise 1x1, BN+ReLU after each."""

    features: int = 64
    stride: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        c = x.shape[-1]
        x = self.cbr(x, c, train=train, kernel=3, stride=self.stride,
                     groups=c, name="dw")
        return self.cbr(x, self.features, train=train, kernel=1, name="pw")


def build_mobilenetv1(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 32, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for entry in MOBILENET_CFG:
        feats, stride = entry if isinstance(entry, tuple) else (entry, 1)
        units.append(DepthwiseSeparable(features=feats, stride=stride,
                                        **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="mobilenetv1")


# ---------------------------------------------------------------------------
# DPN-92
# ---------------------------------------------------------------------------

# per stage: (bottleneck_width, out_planes, num_blocks, dense_depth, stride)
DPN92_CFG = ((96, 256, 3, 16, 1), (192, 512, 4, 32, 2),
             (384, 1024, 20, 24, 2), (768, 2048, 3, 128, 2))


class DPNBlock(_ZooModule):
    """Dual-path block: residual add on the first ``out_planes`` channels,
    dense concatenation of ``dense_depth`` new channels."""

    width: int = 96
    out_planes: int = 256
    dense_depth: int = 16
    stride: int = 1
    first: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool):
        d = self.out_planes
        y = self.cbr(x, self.width, train=train, kernel=1, name="conv0")
        y = self.cbr(y, self.width, train=train, kernel=3, stride=self.stride,
                     groups=32, name="conv1")
        y = self.cbr(y, d + self.dense_depth, train=train, kernel=1,
                     act=False, name="conv2")
        if self.first:
            x = self.conv(d + self.dense_depth, 1, self.stride,
                          name="shortcut")(x)
            x = self.norm("shortcut_bn")(x, train)
        res = x[..., :d] + y[..., :d]
        dense = jnp.concatenate([x[..., d:], y[..., d:]], axis=-1)
        return nn.relu(jnp.concatenate([res, dense], axis=-1))


def build_dpn92(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 64, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for width, out_planes, blocks, dense_depth, stride in DPN92_CFG:
        for b in range(blocks):
            units.append(DPNBlock(
                width=width, out_planes=out_planes, dense_depth=dense_depth,
                stride=(stride if b == 0 else 1), first=(b == 0),
                **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="dpn92")


# ---------------------------------------------------------------------------
# ShuffleNet (G2) and ShuffleNetV2
# ---------------------------------------------------------------------------


class ShuffleV1Block(_ZooModule):
    """Grouped 1x1 → channel shuffle → depthwise 3x3 → grouped 1x1; stride-2
    blocks concatenate an avg-pooled shortcut (ShuffleNet v1, groups=2)."""

    features: int = 200
    groups: int = 2
    stride: int = 1
    first_group: bool = False       # first block of stage 1: ungrouped 1x1

    @nn.compact
    def __call__(self, x, *, train: bool):
        out_features = (self.features - x.shape[-1] if self.stride == 2
                        else self.features)
        mid = max(self.groups, out_features // 4)
        mid -= mid % self.groups
        g_in = 1 if self.first_group else self.groups
        y = self.cbr(x, mid, train=train, kernel=1, groups=g_in, name="conv0")
        y = _channel_shuffle(y, self.groups)
        y = self.cbr(y, mid, train=train, kernel=3, stride=self.stride,
                     groups=mid, act=False, name="dw")
        y = self.cbr(y, out_features, train=train, kernel=1,
                     groups=self.groups, act=False, name="conv1")
        if self.stride == 2:
            short = nn.avg_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            return nn.relu(jnp.concatenate([short, y], axis=-1))
        return nn.relu(y + x)


def build_shufflenetg2(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 24, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for s, (feats, blocks) in enumerate(zip((200, 400, 800), (4, 8, 4))):
        for b in range(blocks):
            units.append(ShuffleV1Block(
                features=feats, groups=2, stride=(2 if b == 0 else 1),
                first_group=(s == 0 and b == 0), **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="shufflenetg2")


class ShuffleV2Block(_ZooModule):
    """ShuffleNetV2 basic (split/concat/shuffle) or down-sampling block."""

    features: int = 116
    stride: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        if self.stride == 1:
            half = x.shape[-1] // 2
            left, right = x[..., :half], x[..., half:]
            f = self.features - half
            right = self.cbr(right, f, train=train, kernel=1, name="r0")
            right = self.cbr(right, f, train=train, kernel=3, groups=f,
                             act=False, name="r_dw")
            right = self.cbr(right, f, train=train, kernel=1, name="r1")
        else:
            f = self.features // 2
            left = self.cbr(x, x.shape[-1], train=train, kernel=3, stride=2,
                            groups=x.shape[-1], act=False, name="l_dw")
            left = self.cbr(left, f, train=train, kernel=1, name="l0")
            right = self.cbr(x, f, train=train, kernel=1, name="r0")
            right = self.cbr(right, f, train=train, kernel=3, stride=2,
                             groups=f, act=False, name="r_dw")
            right = self.cbr(right, self.features - f, train=train, kernel=1,
                             name="r1")
        return _channel_shuffle(jnp.concatenate([left, right], axis=-1), 2)


def build_shufflenetv2(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 24, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for feats, blocks in zip((116, 232, 464), (4, 8, 4)):
        for b in range(blocks):
            units.append(ShuffleV2Block(
                features=feats, stride=(2 if b == 0 else 1), **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=1024,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="shufflenetv2")


# ---------------------------------------------------------------------------
# EfficientNet-B0
# ---------------------------------------------------------------------------

# (expansion, out, num_blocks, kernel, stride)
EFFNET_CFG = ((1, 16, 1, 3, 1), (6, 24, 2, 3, 2), (6, 40, 2, 5, 2),
              (6, 80, 3, 3, 2), (6, 112, 3, 5, 1), (6, 192, 4, 5, 2),
              (6, 320, 1, 3, 1))


class MBConv(_ZooModule):
    """Mobile inverted bottleneck with squeeze-excite and swish."""

    expansion: int = 6
    features: int = 16
    kernel: int = 3
    stride: int = 1
    se_ratio: float = 0.25

    @nn.compact
    def __call__(self, x, *, train: bool):
        c = x.shape[-1]
        hidden = c * self.expansion
        y = x
        if self.expansion != 1:
            y = self.conv(hidden, 1, name="expand")(y)
            y = self.norm("expand_bn")(y, train)
            y = nn.swish(y)
        y = self.conv(hidden, self.kernel, self.stride, groups=hidden,
                      name="dw")(y)
        y = nn.swish(self.norm("dw_bn")(y, train))
        squeezed = max(1, int(c * self.se_ratio))
        w = jnp.mean(y, axis=(1, 2), keepdims=True)
        w = nn.swish(nn.Conv(squeezed, (1, 1), dtype=self.dtype,
                             name="se_fc0")(w))
        w = nn.sigmoid(nn.Conv(hidden, (1, 1), dtype=self.dtype,
                               name="se_fc1")(w))
        y = y * w
        y = self.conv(self.features, 1, name="project")(y)
        y = self.norm("project_bn")(y, train)
        if self.stride == 1 and c == self.features:
            y = y + x
        return y


def build_efficientnetb0(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 32, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for expansion, feats, blocks, kernel, stride in EFFNET_CFG:
        for b in range(blocks):
            units.append(MBConv(
                expansion=expansion, features=feats, kernel=kernel,
                stride=(stride if b == 0 else 1), **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="efficientnetb0")


# ---------------------------------------------------------------------------
# RegNetX-200MF
# ---------------------------------------------------------------------------

# (width, depth, stride), group width 8, bottleneck ratio 1
REGNET_CFG = ((24, 1, 1), (56, 1, 1), (152, 4, 2), (368, 7, 2))


class RegNetBlock(_ZooModule):
    """1x1 → grouped 3x3 → 1x1 residual block (X variant: no SE)."""

    features: int = 24
    stride: int = 1
    group_width: int = 8

    @nn.compact
    def __call__(self, x, *, train: bool):
        groups = self.features // self.group_width
        y = self.cbr(x, self.features, train=train, kernel=1, name="conv0")
        y = self.cbr(y, self.features, train=train, kernel=3,
                     stride=self.stride, groups=groups, name="conv1")
        y = self.cbr(y, self.features, train=train, kernel=1, act=False,
                     name="conv2")
        if self.stride != 1 or x.shape[-1] != self.features:
            x = self.conv(self.features, 1, self.stride, name="shortcut")(x)
            x = self.norm("shortcut_bn")(x, train)
        return nn.relu(y + x)


def build_regnetx_200mf(num_classes: int = 10, **kw) -> StagedModel:
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 64, "kernel": 3, "stride": 1},),
                 **_common(kw))
    ]
    for width, depth, stride in REGNET_CFG:
        for b in range(depth):
            units.append(RegNetBlock(
                features=width, stride=(stride if b == 0 else 1),
                group_width=8, **_common(kw)))
    units.append(ClassifierHead(num_classes=num_classes, conv_features=None,
                                **_common(kw)))
    return StagedModel(units=tuple(units), name="regnetx_200mf")


# ---------------------------------------------------------------------------
# SimpleDLA
# ---------------------------------------------------------------------------


class DLABasic(_ZooModule):
    features: int = 64
    stride: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        y = self.cbr(x, self.features, train=train, kernel=3,
                     stride=self.stride, name="conv0")
        y = self.cbr(y, self.features, train=train, kernel=3, act=False,
                     name="conv1")
        if self.stride != 1 or x.shape[-1] != self.features:
            x = self.conv(self.features, 1, self.stride, name="shortcut")(x)
            x = self.norm("shortcut_bn")(x, train)
        return nn.relu(y + x)


class DLATree(_ZooModule):
    """Deep-layer-aggregation tree: at level 1, two residual blocks whose
    outputs meet at a root (1x1 conv on the concat); higher levels nest
    trees. Self-contained (one input, one output) so it works as a staged
    unit."""

    features: int = 64
    level: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        kw = {k: getattr(self, k) for k in _HPARAM_FIELDS}
        if self.level == 1:
            left = DLABasic(features=self.features, stride=self.stride,
                            name="left", **kw)(x, train=train)
            right = DLABasic(features=self.features, stride=1, name="right",
                             **kw)(left, train=train)
        else:
            left = DLATree(features=self.features, level=self.level - 1,
                           stride=self.stride, name="left", **kw)(
                               x, train=train)
            right = DLATree(features=self.features, level=self.level - 1,
                            stride=1, name="right", **kw)(left, train=train)
        root = jnp.concatenate([left, right], axis=-1)
        root = self.conv(self.features, 1, name="root")(root)
        root = self.norm("root_bn")(root, train)
        return nn.relu(root)


def build_simpledla(num_classes: int = 10, **kw) -> StagedModel:
    c = _common(kw)
    units: list[nn.Module] = [
        ConvUnit(ops=({"features": 16, "kernel": 3, "stride": 1},), **c),
        ConvUnit(ops=({"features": 16, "kernel": 3, "stride": 1},), **c),
        ConvUnit(ops=({"features": 32, "kernel": 3, "stride": 1},), **c),
        DLATree(features=64, level=1, stride=1, **c),
        DLATree(features=128, level=2, stride=2, **c),
        DLATree(features=256, level=2, stride=2, **c),
        DLATree(features=512, level=1, stride=2, **c),
        ClassifierHead(num_classes=num_classes, conv_features=None, **c),
    ]
    return StagedModel(units=tuple(units), name="simpledla")


ZOO_BUILDERS = {
    "vgg11": lambda **kw: build_vgg("vgg11", **kw),
    "vgg13": lambda **kw: build_vgg("vgg13", **kw),
    "vgg16": lambda **kw: build_vgg("vgg16", **kw),
    "vgg19": lambda **kw: build_vgg("vgg19", **kw),
    "preactresnet18": build_preact_resnet18,
    "senet18": build_senet18,
    "googlenet": build_googlenet,
    "densenet121": build_densenet121,
    "resnext29_2x64d": build_resnext29_2x64d,
    "mobilenetv1": build_mobilenetv1,
    "dpn92": build_dpn92,
    "shufflenetg2": build_shufflenetg2,
    "shufflenetv2": build_shufflenetv2,
    "efficientnetb0": build_efficientnetb0,
    "regnetx_200mf": build_regnetx_200mf,
    "simpledla": build_simpledla,
}
