"""Shared flax building blocks for the CNN model zoo.

NHWC layout throughout (TPU-native; XLA tiles NHWC convs onto the MXU
directly). BatchNorm supports three modes, selected by ``bn_mode``:

* ``"local"`` — per-shard batch statistics. Under ``shard_map`` this gives the
  semantics of per-replica BN in ``nn.DataParallel`` / plain DDP (each replica
  normalizes with its own shard's stats).
* ``"sync"``  — cross-replica statistics via ``axis_name`` psum: the
  SyncBatchNorm capability (BASELINE.json config 3; reference ``Readme.md:157``
  discusses the DDP sync-BN prep pass).
* ``"none"``  — no normalization: the reference's ``MobileNetV2_nobn``
  large-batch study variant (``model/mobilenetv2.py:84-148``).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


def _norm(bn_mode: str, *, momentum: float, epsilon: float, dtype,
          axis_name: str | None, name: str):
    """Norm factory. Returns a callable (x, train) -> x."""
    if bn_mode == "none":
        return lambda x, train: x
    bn = nn.BatchNorm(
        use_running_average=None,  # passed at call time
        momentum=momentum,
        epsilon=epsilon,
        dtype=dtype,
        axis_name=axis_name if bn_mode == "sync" else None,
        name=name,
    )
    return lambda x, train: bn(x, use_running_average=not train)


class ConvUnit(nn.Module):
    """Conv → (BN) → (activation), one or more times.

    ``ops`` is a sequence of dicts with keys: features, kernel, stride,
    groups, act (bool), norm (bool — set False for a bare conv, e.g. the
    pre-activation stems where the first block's BN comes first), and
    maxpool (int — stride of a trailing 3x3 SAME max-pool, e.g. the
    ImageNet ResNet stem's pool; 0/absent = none). A
    ``feature_group_count == features`` conv is a depthwise conv
    (MXU-friendly form of the reference's ``groups=planes`` depthwise,
    ``model/mobilenetv2.py:19``).
    """

    ops: Sequence[dict]
    bn_mode: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | None = None
    activation: Callable = nn.relu

    @nn.compact
    def __call__(self, x, *, train: bool):
        for i, op in enumerate(self.ops):
            normed = op.get("norm", True)
            x = nn.Conv(
                features=op["features"],
                kernel_size=(op.get("kernel", 3),) * 2,
                strides=(op.get("stride", 1),) * 2,
                padding=op.get("padding", "SAME"),
                feature_group_count=op.get("groups", 1),
                use_bias=self.bn_mode == "none" or not normed,
                dtype=self.dtype,
                name=f"conv{i}",
            )(x)
            if normed:
                x = _norm(self.bn_mode, momentum=self.bn_momentum,
                          epsilon=self.bn_epsilon, dtype=self.dtype,
                          axis_name=self.axis_name, name=f"bn{i}")(x, train)
            if op.get("act", True):
                x = self.activation(x)
            if op.get("maxpool"):
                s = op["maxpool"]
                x = nn.max_pool(x, (3, 3), strides=(s, s), padding="SAME")
        return x


class ClassifierHead(nn.Module):
    """(Conv 1x1 expand) → ReLU → global/window avg-pool → flatten → Dense.

    The reference's tail: ``conv2(1x1,1280)+bn2`` then ``Reshape1`` =
    relu → avg_pool(4) → flatten, then ``linear`` (``model/mobilenetv2.py:
    60-61,74-76,150-158``; pipeline use ``model_parallel.py:143-144``).
    """

    num_classes: int
    conv_features: int | None = None     # e.g. 1280 for MobileNetV2; None=skip
    pool: str = "avg"                    # "avg" = global average pool
    bn_mode: str = "local"
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, *, train: bool):
        if self.conv_features is not None:
            x = nn.Conv(self.conv_features, (1, 1), use_bias=self.bn_mode == "none",
                        dtype=self.dtype, name="conv")(x)
            x = _norm(self.bn_mode, momentum=self.bn_momentum,
                      epsilon=self.bn_epsilon, dtype=self.dtype,
                      axis_name=self.axis_name, name="bn")(x, train)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))     # global average pool → (N, C)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="linear")(x)
        return x
