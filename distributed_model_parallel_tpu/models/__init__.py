"""Model zoo registry.

Mirrors the capability of the reference's model package
(``model/__init__.py``, ``model/mobilenetv2.py``) plus the models promoted to
scope by BASELINE.json (ResNet-18/50) and the Transformer LM flagship used for
multi-axis mesh parallelism and long-context.
"""

from __future__ import annotations

import jax.numpy as jnp

from distributed_model_parallel_tpu.config import ModelConfig
from distributed_model_parallel_tpu.models.staged import (  # noqa: F401
    StagedModel,
    balanced_boundaries,
    merge_tree,
    partition_tree,
    stage_slices,
)
from distributed_model_parallel_tpu.models.mobilenetv2 import build_mobilenetv2
from distributed_model_parallel_tpu.models.resnet import build_resnet

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def _cnn_kwargs(config: ModelConfig, axis_name: str | None):
    bn_mode = config.batchnorm
    if bn_mode == "sync" and axis_name is None:
        raise ValueError("sync BatchNorm requires an axis_name")
    return dict(
        num_classes=config.num_classes,
        bn_mode=bn_mode,
        bn_momentum=config.bn_momentum,
        bn_epsilon=config.bn_epsilon,
        dtype=_DTYPES[config.dtype],
        axis_name=axis_name,
    )


def get_model(config: ModelConfig, *, axis_name: str | None = None) -> StagedModel:
    """Build a StagedModel from a ModelConfig.

    ``axis_name`` is the mesh axis for cross-replica BatchNorm statistics;
    only consulted when ``config.batchnorm == "sync"``.
    """
    name = config.name
    # extra={"input_layout": "imagenet"} selects native-resolution stride
    # tables (224px finetune workload). Only mobilenetv2/resnet have them;
    # every other family REJECTS a non-default layout rather than silently
    # running its CIFAR strides under an "imagenet" label.
    extra = dict(config.extra)
    layout = extra.pop("input_layout", "cifar")
    if "input_layout" in config.extra and name not in (
            "mobilenetv2", "mobilenetv2_nobn",
            "resnet18", "resnet34", "resnet50"):
        # Reject even an explicit "cifar" for families without the knob:
        # the transformer/embedding builders splat config.extra raw and
        # would die on the stray key with a confusing TypeError.
        raise ValueError(
            f"model {name!r} takes no input_layout "
            f"(only mobilenetv2/resnet18/34/50 do)")
    if name in ("mobilenetv2", "mobilenetv2_nobn"):
        kw = _cnn_kwargs(config, axis_name)
        if name.endswith("_nobn"):
            kw["bn_mode"] = "none"
        return build_mobilenetv2(**kw, input_layout=layout)
    if name in ("resnet18", "resnet34", "resnet50"):
        return build_resnet(name, **_cnn_kwargs(config, axis_name),
                            input_layout=layout)
    if name == "tinycnn":
        from distributed_model_parallel_tpu.models.tinycnn import build_tinycnn
        return build_tinycnn(**_cnn_kwargs(config, axis_name), **extra)
    if name == "transformer":
        from distributed_model_parallel_tpu.models.transformer import build_transformer
        return build_transformer(config)
    if name == "embedding_bow":
        from distributed_model_parallel_tpu.models.embedding import build_embedding_bow
        return build_embedding_bow(config)
    from distributed_model_parallel_tpu.models.zoo import ZOO_BUILDERS
    if name in ZOO_BUILDERS:
        return ZOO_BUILDERS[name](**_cnn_kwargs(config, axis_name))
    raise KeyError(
        f"unknown model {name!r}; known: mobilenetv2[_nobn], resnet18/34/50, "
        f"tinycnn, transformer, embedding_bow, "
        f"{', '.join(sorted(ZOO_BUILDERS))}")
