"""Stage-able model representation.

The reference hard-codes its pipeline split per-rank in the entry script
(``model_parallel.py:102-144``: rank 0 = conv1+bn1+layers[0:3], middle ranks =
``layers[6*rank-3 : 6*rank+3]``, last = layers[15:]+conv2+bn2+Reshape1+linear),
which only works because its MobileNetV2 is a flat ``nn.Sequential``
(``model/mobilenetv2.py:62-68``). Here the same idea is first-class data: every
model is an ordered tuple of *units* (flax modules), and a stage partition is
just a list of unit-index boundaries. Pipeline, data-parallel and single-device
execution all consume the same representation.

Parameters are a tuple of per-unit variable dicts — a plain pytree, so optax,
jit, shardings and checkpointing all work unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

# Per-unit variables: {"params": {...}, "batch_stats": {...}} (batch_stats may
# be absent for norm-free units).
UnitVars = dict[str, Any]
Params = tuple[Any, ...]        # tuple over units of params subtrees
State = tuple[Any, ...]         # tuple over units of batch_stats subtrees ({} if none)


@dataclasses.dataclass(frozen=True)
class StagedModel:
    """An ordered sequence of flax unit modules with functional apply.

    ``units[i]`` must be callable as ``unit.apply(variables, x, train=...)``
    and may carry ``batch_stats`` state (BatchNorm running averages).
    """

    units: tuple[nn.Module, ...]
    name: str = "staged"

    @property
    def num_units(self) -> int:
        return len(self.units)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array, sample: jax.Array) -> tuple[Params, State]:
        """Initialize all units by threading a sample batch through them."""
        params, state = [], []
        x = sample
        for i, unit in enumerate(self.units):
            rng, sub = jax.random.split(rng)
            variables = unit.init(sub, x, train=False)
            params.append(variables.get("params", {}))
            state.append(variables.get("batch_stats", {}))
            x = unit.apply(variables, x, train=False)
        return tuple(params), tuple(state)

    def output_shape(self, sample_shape: Sequence[int]) -> tuple[int, ...]:
        """Shape of the final output for a given input shape (eval_shape)."""
        def run(x):
            p, s = self.init(jax.random.key(0), x)
            y, _ = self.apply(p, s, x, train=False)
            return y
        return tuple(jax.eval_shape(run, jnp.zeros(sample_shape)).shape)

    # -- apply --------------------------------------------------------------
    def apply_unit(self, i: int, params_i, state_i, x, *, train: bool):
        """Apply unit i. Returns (y, new_state_i)."""
        variables = {"params": params_i}
        has_state = bool(state_i)
        if has_state:
            variables["batch_stats"] = state_i
        if train and has_state:
            y, updated = self.units[i].apply(
                variables, x, train=True, mutable=["batch_stats"])
            return y, updated["batch_stats"]
        y = self.units[i].apply(variables, x, train=train and not has_state)
        return y, state_i

    def apply_range(self, params: Params, state: State, x, lo: int, hi: int,
                    *, train: bool):
        """Apply units [lo, hi). Returns (y, new_state_slice)."""
        new_state = list(state[lo:hi])
        for i in range(lo, hi):
            x, new_state[i - lo] = self.apply_unit(
                i, params[i], state[i], x, train=train)
        return x, tuple(new_state)

    def apply(self, params: Params, state: State, x, *, train: bool):
        """Full forward. Returns (logits, new_state)."""
        return self.apply_range(params, state, x, 0, self.num_units, train=train)


def balanced_boundaries(num_units: int, num_stages: int) -> list[int]:
    """Split ``num_units`` units into ``num_stages`` contiguous stages.

    Returns boundaries ``b`` of length num_stages+1 with b[0]=0,
    b[-1]=num_units; stage s owns units [b[s], b[s+1]). Remainder units go to
    the earliest stages (front-loaded, like the reference's split which gives
    rank 0 the stem plus the first blocks, ``model_parallel.py:102-104``).
    """
    if not (1 <= num_stages <= num_units):
        raise ValueError(f"cannot split {num_units} units into {num_stages} stages")
    base, rem = divmod(num_units, num_stages)
    bounds = [0]
    for s in range(num_stages):
        bounds.append(bounds[-1] + base + (1 if s < rem else 0))
    return bounds


def stage_slices(num_units: int, num_stages: int,
                 boundaries: Sequence[int] | None = None) -> list[tuple[int, int]]:
    """(lo, hi) unit ranges per stage, honoring explicit boundaries if given."""
    if boundaries is None:
        b = balanced_boundaries(num_units, num_stages)
    else:
        b = list(boundaries)
        if b[0] != 0 or b[-1] != num_units or len(b) != num_stages + 1:
            raise ValueError(
                f"boundaries {b} invalid for {num_units} units / {num_stages} stages")
        if any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"boundaries {b} must be strictly increasing")
    return [(b[s], b[s + 1]) for s in range(num_stages)]


def partition_tree(tree: tuple, slices: Sequence[tuple[int, int]]) -> list[tuple]:
    """Split a per-unit tuple pytree into per-stage tuples."""
    return [tuple(tree[lo:hi]) for lo, hi in slices]


def merge_tree(parts: Sequence[tuple]) -> tuple:
    """Inverse of partition_tree."""
    out: list = []
    for p in parts:
        out.extend(p)
    return tuple(out)
