#!/usr/bin/env python
"""Export telemetry streams to Chrome-trace / Perfetto JSON.

One zoomable timeline from the typed records the stack already writes:

* ``span`` records (utils/tracing.py) become complete ("X") events —
  trainer epochs/drains/evals, checkpoint I/O, engine prefill chunks and
  decode rounds, orchestrator rounds — nested by the span stack's
  parent/child structure (same thread track, time containment);
* ``serve`` completed records become per-request lifecycle bars:
  queue → prefill → decode segments reconstructed from the record's
  queue_wait/ttft/wall accounting, one row per request;
* point records (failure, recovery, fault, consistency, resume, tenant,
  health, gate) become instant events on their lane;
* ``step`` records become counter tracks (step_time_ms, throughput).

Lanes: one Chrome "process" per tenant (untagged records share the
run's own lane), one "thread" per recording thread — so a fleet merge
renders every tenant's timeline stacked in one view, and the exported
file loads directly in ``chrome://tracing`` / https://ui.perfetto.dev
next to an xplane device trace.

Usage:
  python scripts/dmp_trace.py log/lm.jsonl -o /tmp/lm_trace.json
  python scripts/dmp_trace.py fleet/fleet.jsonl t0/log/t0.jsonl -o fleet.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    merge_streams,
    read_records,
)

# Point-record kinds rendered as instant events, with the field that
# names the event in the UI.
INSTANT_KINDS = {
    "failure": "error",
    "recovery": "action",
    "fault": "fault",
    "consistency": "status",
    "resume": "slot",
    "tenant": "event",
    "health": "event",
    "event": "message",
    "gate": "ok",
    "plan": "strategy",
}


class _Lanes:
    """Stable pid/tid assignment: one pid per tenant lane, one tid per
    (lane, thread) pair, with Chrome metadata naming both."""

    def __init__(self, events: list):
        self.events = events
        self._pids: dict[str, int] = {}
        self._tids: dict[tuple[str, str], int] = {}

    def pid(self, lane: str) -> int:
        if lane not in self._pids:
            self._pids[lane] = len(self._pids)
            self.events.append({"ph": "M", "name": "process_name",
                                "pid": self._pids[lane], "ts": 0,
                                "args": {"name": lane}})
        return self._pids[lane]

    def tid(self, lane: str, thread: str) -> int:
        key = (lane, thread)
        if key not in self._tids:
            self._tids[key] = len(self._tids) + 1
            self.events.append({"ph": "M", "name": "thread_name",
                                "pid": self.pid(lane),
                                "tid": self._tids[key], "ts": 0,
                                "args": {"name": thread}})
        return self._tids[key]


def _lane(r: dict, default: str) -> str:
    return str(r.get("tenant") or default)


def build_trace(records: list[dict]) -> dict:
    """Chrome trace object ({"traceEvents": [...]}) for a record list
    (one stream's records, or a ts-ordered fleet merge)."""
    runs = [r for r in records if r.get("kind") == "run_start"]
    default_lane = str((runs[0].get("run") if runs else None) or "run")
    # Time origin: earliest wall-clock instant in the stream (span starts
    # included — a span can begin before the first point record lands).
    t_candidates = [r["ts"] for r in records
                    if isinstance(r.get("ts"), (int, float))]
    t_candidates += [r["t0"] for r in records if r.get("kind") == "span"
                     and isinstance(r.get("t0"), (int, float))]
    t_candidates += [r["ts"] - r["wall_s"] for r in records
                     if r.get("kind") == "serve"
                     and r.get("event") == "completed"
                     and isinstance(r.get("ts"), (int, float))
                     and isinstance(r.get("wall_s"), (int, float))]
    base = min(t_candidates, default=0.0)

    def us(t: float) -> float:
        return round((t - base) * 1e6, 1)

    events: list[dict] = []
    lanes = _Lanes(events)
    req_tids: dict[tuple[str, str], int] = {}
    for r in records:
        kind = r.get("kind")
        lane = _lane(r, default_lane)
        if kind == "span" and isinstance(r.get("t0"), (int, float)) \
                and isinstance(r.get("dur_s"), (int, float)):
            args = {k: v for k, v in r.items()
                    if k not in ("kind", "ts", "t0", "dur_s", "name",
                                 "thread", "tenant")}
            events.append({
                "ph": "X", "name": str(r.get("name")),
                "cat": "span", "ts": us(r["t0"]),
                "dur": round(r["dur_s"] * 1e6, 1),
                "pid": lanes.pid(lane),
                "tid": lanes.tid(lane, str(r.get("thread") or "main")),
                "args": args,
            })
        elif kind == "serve" and r.get("event") == "completed" \
                and isinstance(r.get("ts"), (int, float)) \
                and isinstance(r.get("wall_s"), (int, float)):
            # Reconstruct the request lifecycle from the SLO accounting:
            # arrival = completion ts - wall; queue wait, TTFT and the
            # decode tail partition the bar. One Chrome thread row per
            # request keeps concurrent requests visually parallel.
            rid = str(r.get("request"))
            key = (lane, rid)
            if key not in req_tids:
                req_tids[key] = lanes.tid(lane, f"request {rid}")
            tid = req_tids[key]
            arrive = r["ts"] - r["wall_s"]
            qw = r.get("queue_wait_s") or 0.0
            ttft = r.get("ttft_s")
            segs = [("queue", arrive, qw)]
            if isinstance(ttft, (int, float)) and ttft >= qw:
                segs.append(("prefill", arrive + qw, ttft - qw))
                segs.append(("decode", arrive + ttft,
                             max(0.0, r["wall_s"] - ttft)))
            pid = lanes.pid(lane)
            for name, t0, dur in segs:
                if dur <= 0:
                    continue
                events.append({
                    "ph": "X", "name": name, "cat": "serve-request",
                    "ts": us(t0), "dur": round(dur * 1e6, 1),
                    "pid": pid, "tid": tid,
                    "args": {"request": rid,
                             "new_tokens": r.get("new_tokens"),
                             "prompt_tokens": r.get("prompt_tokens"),
                             "policy": r.get("policy")},
                })
        elif kind == "step" and isinstance(r.get("ts"), (int, float)):
            pid = lanes.pid(lane)
            if isinstance(r.get("step_time_s"), (int, float)):
                events.append({
                    "ph": "C", "name": "step_time_ms", "pid": pid,
                    "ts": us(r["ts"]),
                    "args": {"ms": round(r["step_time_s"] * 1e3, 3)}})
            for k in ("samples_per_s", "tokens_per_s"):
                if isinstance(r.get(k), (int, float)):
                    events.append({
                        "ph": "C", "name": k, "pid": pid,
                        "ts": us(r["ts"]), "args": {k: round(r[k], 1)}})
        elif kind in INSTANT_KINDS and isinstance(r.get("ts"),
                                                  (int, float)):
            label = r.get(INSTANT_KINDS[kind])
            events.append({
                "ph": "i", "name": f"{kind}:{label}", "cat": kind,
                "ts": us(r["ts"]), "s": "p",
                "pid": lanes.pid(lane),
                "args": {k: v for k, v in r.items()
                         if k not in ("kind", "ts", "tenant")
                         and isinstance(v, (str, int, float, bool))},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"origin_unix_s": base,
                          "exporter": "scripts/dmp_trace.py"}}


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="Export telemetry stream(s) to Chrome-trace JSON")
    p.add_argument("jsonl", nargs="+",
                   help="telemetry stream(s); several merge into one "
                        "tenant-laned fleet timeline")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: stdout)")
    args = p.parse_args(argv)
    for path in args.jsonl:
        if not os.path.exists(path):
            raise SystemExit(f"no such telemetry file: {path}")
    records = (merge_streams(args.jsonl) if len(args.jsonl) > 1
               else read_records(args.jsonl[0]))
    if not records:
        raise SystemExit("no parseable records in any stream")
    trace = build_trace(records)
    out = json.dumps(trace)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
        n_span = sum(1 for e in trace["traceEvents"]
                     if e.get("cat") == "span")
        print(f"{args.out}: {len(trace['traceEvents'])} events "
              f"({n_span} spans) — load in chrome://tracing or "
              f"https://ui.perfetto.dev")
    else:
        print(out)


if __name__ == "__main__":
    main()
