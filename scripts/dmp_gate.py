#!/usr/bin/env python
"""Cross-run perf regression gate: fail the run when it got slower.

Compares a fresh run's telemetry stream (or a committed ``BENCH_*.json``
artifact) against the baseline ledger's recent green history with a
noise band (median ± k·MAD, floored at ``--rel-floor`` of the median —
utils/baseline.py), writes one typed ``gate`` record onto the stream
naming the offending metric and the span/phase whose share grew most,
and exits nonzero on regression. This is ROADMAP item 4's "make speed a
regression gate" as a command:

Usage:
  # seed the ledger once from the checked-in artifacts
  python scripts/dmp_gate.py --seed 'BENCH_*.json' 'MULTICHIP_*.json' \
      --ledger BASELINE_LEDGER.jsonl

  # gate a fresh bench/trainer stream (rc 1 on regression)
  python scripts/dmp_gate.py /tmp/dmp_bench_log/bench_telemetry.jsonl

  # gate and, when green, append this run to the ledger
  python scripts/dmp_gate.py log/lm.jsonl --update

Exit codes: 0 pass (or warn-only), 1 regression, 2 nothing to gate
(no measurable records in the stream). ``bench.py`` runs this gate
automatically after every headline measurement (warn-only by default,
``DMP_BENCH_GATE=strict`` to fail).
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.utils import baseline  # noqa: E402
from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    read_records,
)

DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BASELINE_LEDGER.jsonl")


def _is_artifact(path: str) -> bool:
    """A committed bench artifact is ONE json object; a telemetry stream
    is JSONL whose records carry ``kind``. Sniff the first line (however
    long): a complete record with ``kind`` is a stream; a lone
    kind-less object (compact artifact) or a multi-line object that
    only parses whole (pretty-printed artifact) is an artifact."""
    with open(path) as f:
        first = f.readline()
        rest = f.read(1)
    try:
        obj = json.loads(first)
        if isinstance(obj, dict) and "kind" in obj:
            return False                       # a telemetry record
        return not rest                        # single-line whole object
    except json.JSONDecodeError:
        pass
    try:
        with open(path) as f:
            json.load(f)
        return True                            # pretty-printed artifact
    except json.JSONDecodeError:
        return False                           # torn stream: JSONL path


def seed(ledger_path: str, patterns: list[str]) -> int:
    """Ingest committed artifacts into the ledger, skipping sources
    already present (idempotent — re-seeding must not double history)."""
    existing = {e.get("source") for e in baseline.load_ledger(ledger_path)}
    added = 0
    for pat in patterns:
        for path in sorted(globlib.glob(pat)):
            if os.path.basename(path) in existing:
                continue
            added += baseline.append_entries(
                ledger_path, baseline.ingest_artifact(path))
    return added


def describe(result: dict) -> str:
    lines = []
    for v in result["verdicts"]:
        band = (f"baseline {v['baseline']:g} ± {v['tolerance']:g} "
                f"(n={v['n_history']})")
        mark = "ok " if v["ok"] else "REGRESSED"
        lines.append(f"  {mark} {v['metric']:<52} {v['value']:g} vs {band}")
        attr = v.get("attribution")
        if attr:
            what = attr.get("span") or attr.get("phase")
            kind = "span" if "span" in attr else "phase"
            lines.append(
                f"      -> {kind} {what!r} grew "
                f"{attr['baseline_share']:.1%} -> {attr['share']:.1%} "
                f"of the run — look there first")
    for key in result["no_baseline"]:
        lines.append(f"  --  {key}: no green baseline in the ledger "
                     f"(first run for this key — nothing to regress "
                     f"against)")
    verdict = "PASS" if result["ok"] else "REGRESSION"
    lines.append(f"gate: {verdict} "
                 f"({len(result['regressions'])} regressed / "
                 f"{len(result['verdicts'])} checked, "
                 f"k={result['k']:g} rel_floor={result['rel_floor']:g})")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Gate a fresh run's performance against the baseline "
                    "ledger's noise band")
    p.add_argument("stream", nargs="?",
                   help="telemetry JSONL stream or committed BENCH_*.json "
                        "artifact to gate")
    p.add_argument("--ledger", default=DEFAULT_LEDGER,
                   help=f"baseline ledger path (default {DEFAULT_LEDGER})")
    p.add_argument("--seed", nargs="+", metavar="GLOB", default=None,
                   help="ingest committed BENCH_*/MULTICHIP_* artifacts "
                        "into the ledger (idempotent by source filename)")
    p.add_argument("--k", type=float, default=baseline.DEFAULT_K,
                   help="noise-band width in robust sigmas (k * 1.4826*MAD)")
    p.add_argument("--rel-floor", type=float,
                   default=baseline.DEFAULT_REL_FLOOR,
                   help="minimum band half-width as a fraction of the "
                        "baseline median (shields a MAD-0 history)")
    p.add_argument("--history", type=int, default=baseline.DEFAULT_HISTORY,
                   help="how many recent green entries form the band")
    p.add_argument("--update", action="store_true",
                   help="append this run to the ledger when the gate "
                        "passes (grows the history one green sample)")
    p.add_argument("--warn-only", action="store_true",
                   help="report regressions but exit 0 (bench.py's "
                        "default posture)")
    p.add_argument("--no-record", action="store_true",
                   help="do not append the typed gate record to the stream")
    args = p.parse_args(argv)

    if args.seed is not None:
        added = seed(args.ledger, args.seed)
        print(f"ledger {args.ledger}: +{added} entries "
              f"({len(baseline.load_ledger(args.ledger))} total)")
        if args.stream is None:
            return 0
    if args.stream is None:
        p.error("nothing to do: pass a stream to gate and/or --seed")
    if not os.path.exists(args.stream):
        raise SystemExit(f"no such stream/artifact: {args.stream}")

    is_artifact = _is_artifact(args.stream)
    if is_artifact:
        entries = baseline.ingest_artifact(args.stream)
        points = [{
            "metric": e["metric"], "unit": e.get("unit"),
            "plan": e.get("plan"), "key": e["key"],
            "metrics": e.get("metrics") or {},
            "span_shares": None, "phases": e.get("phases"),
        } for e in entries if e.get("green")]
    else:
        recs = read_records(args.stream)
        # A stream appended across invocations (bench's default path, a
        # resumed trainer's attempts) holds several runs; gate only the
        # FRESH one — records from the last run_start header on — or
        # stale runs would skew the p50/span shares and --update would
        # append one duplicate ledger entry per historical run.
        last = max((i for i, r in enumerate(recs)
                    if r.get("kind") == "run_start"), default=0)
        points = baseline.extract_points(recs[last:])
    if not points or not any(pt["metrics"] for pt in points):
        print(f"{args.stream}: no headline metrics to gate (need bench "
              f"records or step records with timings)", file=sys.stderr)
        return 2

    ledger = baseline.load_ledger(args.ledger)
    result = baseline.gate_points(points, ledger, k=args.k,
                                  rel_floor=args.rel_floor,
                                  history=args.history)
    if not args.no_record and not is_artifact:
        baseline.emit_gate_record(args.stream, result,
                                  ledger_path=args.ledger)
    print(describe(result))
    if result["ok"] and args.update:
        n = baseline.append_entries(
            args.ledger,
            baseline.entries_from_points(
                points, green=True, source=os.path.basename(args.stream)))
        print(f"ledger {args.ledger}: +{n} green entries")
    if not result["ok"] and not args.warn_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
