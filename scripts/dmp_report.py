#!/usr/bin/env python
"""Run report: one command that answers "why is this step slow".

Joins a run's telemetry JSONL (utils/telemetry.TelemetryRun — written by
every trainer via RunLogger and by bench.py) with an optional xplane trace
directory (utils/xplane op breakdown) and prints:

* step-time percentiles (p50/p90/p99) and throughput from ``step`` records;
* step phase breakdown (``step_phase`` records from bench.py): host-input /
  h2d / device seconds per step + the pipeline-active proof (device
  prefetch lead, donation aliases, grad bucketing, fused optimizer) —
  "phase timing unavailable" on runs that could not attribute (CPU);
* comm/compute overlap from the xplane device timeline (``--trace``): the
  comm-hidden fraction — how much of the collective time the backward
  actually covered;
* serving SLOs (``serve`` records from serve/engine.py — per-request
  TTFT / queue-wait / per-token-latency percentiles, tokens/s, slot
  utilization, page-pool occupancy per engine run) on streams written by
  BENCH_serve or any engine with a telemetry stream attached;
* MFU against the profiling.py peak tables — or an honest "MFU unavailable"
  line when the device has no peak entry (CPU) or the run recorded no FLOPs;
* HBM-roofline position when the run recorded demand bytes;
* communication volume AND message counts per collective kind x mesh axis
  (trace-time ring-model estimates from ops/collectives.py — the beta and
  alpha terms the autotuner's cost model prices with);
* the parallelism-plan timeline (``plan`` records from the autotuner,
  autotune/planner.py): chosen layout, cost breakdown, alternatives, and
  the global step each (re-)plan landed at;
* the span-time rollup (``span`` records, utils/tracing.py) and the
  latest regression-gate verdict (``gate`` records, utils/baseline.py)
  — the zoomable versions are scripts/dmp_trace.py and
  scripts/dmp_gate.py (docs/TRACING.md);
* device memory watermarks and recompilation counts;
* the failure/recovery/divergence timeline (injected faults, non-finite
  restores, stall escalations, torn-checkpoint fallbacks, cross-replica
  divergence detections + repairs — train/resilience.py,
  train/consistency.py);
* on fleet reports: the device-health timeline (score transitions,
  quarantines, proactive migrations, grow-backs — utils/health.py);
* top-N device ops + per-category device time from the xplane trace
  (``--trace``), degrading to an actionable one-liner when the tensorflow
  proto bindings are absent.

Usage:
  python scripts/dmp_report.py log/lm.jsonl
  python scripts/dmp_report.py log/train.jsonl --trace /tmp/dmp_step_trace
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    join_request_traces,
    read_records,
)


def percentile(xs: list[float], q: float) -> float:
    """Linear-interpolated percentile of a non-empty list (no numpy dep in
    the report path — the stream is host data)."""
    ys = sorted(xs)
    if len(ys) == 1:
        return ys[0]
    pos = q / 100.0 * (len(ys) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ys) - 1)
    return ys[lo] + (pos - lo) * (ys[hi] - ys[lo])


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(b) < 1024 or unit == "TB":
            return f"{b:.1f} {unit}"
        b /= 1024
    return f"{b:.1f} TB"


def _fmt_s(s: float) -> str:
    return f"{s * 1e3:.2f} ms" if s < 1 else f"{s:.3f} s"


def _by_kind(records: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for r in records:
        # Legacy streams (pre-telemetry RunLogger) had no "kind": treat
        # records carrying an epoch as epoch records so old logs still
        # render a (reduced) report.
        kind = r.get("kind") or ("epoch" if "epoch" in r else "event")
        out.setdefault(kind, []).append(r)
    return out


def _steps_section(lines: list[str], steps: list[dict]) -> list[float]:
    """Append the step-timing section; returns the step-time list so the
    efficiency section reuses the same filtered values."""
    lines.append(f"== steps ({len(steps)} records) ==")
    times = [r["step_time_s"] for r in steps
             if isinstance(r.get("step_time_s"), (int, float))]
    if times:
        lines.append(
            f"step time   p50 {_fmt_s(percentile(times, 50))}   "
            f"p90 {_fmt_s(percentile(times, 90))}   "
            f"p99 {_fmt_s(percentile(times, 99))}   "
            f"mean {_fmt_s(sum(times) / len(times))}")
    else:
        lines.append("step time   (no step_time_s keys recorded)")
    data = [r["data_time_s"] for r in steps
            if isinstance(r.get("data_time_s"), (int, float))]
    if data:
        tot = sum(data) + sum(times)
        lines.append(
            f"data time   mean {_fmt_s(sum(data) / len(data))}"
            + (f"   (data/compute split {sum(data) / tot:.1%} data)"
               if tot > 0 else ""))
    for key, unit in (("tokens_per_s", "tokens/s"),
                      ("samples_per_s", "samples/s")):
        vals = [r[key] for r in steps
                if isinstance(r.get(key), (int, float))]
        if vals:
            lines.append(f"throughput  mean {sum(vals) / len(vals):,.1f} "
                         f"{unit}   max {max(vals):,.1f} {unit}")
    return times


def _mfu_section(lines: list[str], meta: dict, device: dict,
                 by_kind: dict, times: list[float]) -> None:
    from distributed_model_parallel_tpu.utils.profiling import (
        TPU_PEAK_FLOPS,
        TPU_PEAK_HBM_BYTES,
        match_device_kind,
    )

    lines.append("== efficiency ==")
    kind = device.get("device_kind", "") or device.get("platform", "?")
    n_dev = max(1, int(device.get("n_devices", 1) or 1))
    peak = match_device_kind(TPU_PEAK_FLOPS, kind=kind)
    # Global analytic FLOPs (trainer/LM-bench meta) or per-device
    # cost-analysis FLOPs (CNN bench "cost_analysis" record).
    flops_global = meta.get("model_flops_per_step")
    ca = (by_kind.get("cost_analysis") or [{}])[-1]
    flops_device = ca.get("device_flops_per_step")
    if not times:
        lines.append("MFU unavailable (no step-time records)")
    elif peak is None:
        lines.append(f"MFU unavailable (no peak-FLOPs table entry for "
                     f"device_kind={kind!r} — expected on CPU)")
    elif not (flops_global or flops_device):
        lines.append("MFU unavailable (run recorded no FLOPs-per-step; the "
                     "LM trainer and bench.py record them)")
    else:
        t50 = percentile(times, 50)
        per_chip = (flops_device if flops_device
                    else flops_global / n_dev)
        lines.append(f"MFU {per_chip / t50 / peak:.3f}  "
                     f"({per_chip / 1e12:.2f} TF/chip/step at p50 "
                     f"{_fmt_s(t50)} vs {peak / 1e12:.0f} TF/s peak "
                     f"[{kind}])")
    hbm_peak = match_device_kind(TPU_PEAK_HBM_BYTES, kind=kind)
    bytes_step = ca.get("bytes_accessed_per_step")
    if bytes_step and times and hbm_peak:
        from distributed_model_parallel_tpu.utils.profiling import (
            demand_frac_of_peak,
        )

        rate = bytes_step / percentile(times, 50)
        frac, frac_err = demand_frac_of_peak(rate, hbm_peak)
        if frac_err:
            # A fraction of the physical peak > 1 is not a roofline
            # position, it is proof the measurement overcounted
            # (BENCH_r04 published 1.457x as fact) — the shared policy
            # in utils/profiling.demand_frac_of_peak refuses it.
            lines.append(f"HBM roofline: MEASUREMENT ERROR — {frac_err}")
        else:
            lines.append(
                f"HBM roofline: demand {rate / 1e9:.0f} GB/s vs "
                f"{hbm_peak / 1e9:.0f} GB/s peak ({frac:.2f}x) — "
                f"demand-side estimate (analytic bytes / measured time), "
                f"not a hardware counter")
    elif bytes_step:
        lines.append("HBM roofline unavailable (no peak-bandwidth entry "
                     f"for device_kind={kind!r})")


def _phase_section(lines: list[str], by_kind: dict) -> None:
    """Step phase breakdown (bench.py ``step_phase`` records): where a
    step's wall time goes — host batch assembly, host→device transfer,
    device compute — plus the no-silent-fallback proof that the raw-speed
    levers (device prefetch, donation, bucketed grads, fused optimizer)
    are active. Renders "phase timing unavailable" honestly when the run
    could not attribute (CPU: no h2d/device boundary)."""
    recs = by_kind.get("step_phase") or []
    if not recs:
        return
    r = recs[-1]
    lines.append("== step phase breakdown ==")
    pipe = r.get("pipeline")
    if pipe and pipe.get("workload"):
        # Decode/serve-flavored record: the pipeline identity is its own
        # key set (batch, prompt/gen lengths, cache kind) — render as-is.
        lines.append("pipeline: " + "  ".join(
            f"{k}={v}" for k, v in pipe.items()))
    elif pipe:
        lines.append(
            (f"pipeline: input={pipe.get('input_path')}"
             if pipe.get("input_path") else "pipeline:")
            + f"  device_prefetch={pipe.get('device_prefetch_depth')}"
            + (f" (max lead observed "
               f"{pipe.get('device_prefetch_max_lead')}"
               + (", streaming-path probe — the timed loop is "
                  "device-resident)"
                  if pipe.get("device_resident_data") else ")")
               if pipe.get("device_prefetch_max_lead") is not None else "")
            + f"  host_prefetch={pipe.get('host_prefetch_depth')}"
            + (f"  steps_per_dispatch={pipe.get('steps_per_dispatch')}"
               if pipe.get("device_resident_data") else "")
            + f"  grad={pipe.get('grad_reduction')}"
            + f"  fused_opt={pipe.get('fused_optimizer')}")
        dropped = pipe.get("donation_dropped") or []
        lines.append(
            f"donation: {pipe.get('donation_aliases')} input→output "
            f"aliases committed"
            + (f", dropped {dropped}" if dropped else ", none dropped"))
    phases = r.get("phases")
    if not phases:
        lines.append("phase timing unavailable"
                     + (f" ({r.get('reason')})" if r.get("reason") else ""))
        return
    # Training records carry host-input/h2d/device; the decode bench's
    # record carries prefill/decode_token/sample — render whatever
    # ``*_s`` phases the record holds, in record order.
    keys = [k for k in phases
            if k.endswith("_s") and isinstance(phases.get(k), (int, float))]
    total = sum(phases[k] for k in keys)
    # Training records are per-step; decode records are per generate run
    # (uniform within each record, so the shares are honest either way).
    unit = "/run" if pipe and pipe.get("workload") else "/step"
    for key in keys:
        v = phases[key]
        label = key[:-2].replace("_", "-")
        share = f" ({v / total:5.1%})" if total > 0 else ""
        lines.append(f"  {label:12s} {_fmt_s(v):>10s}{unit}{share}")
    lines.append(f"  (serialized attribution probe over "
                 f"{phases.get('n_steps')} steps — phases cannot hide "
                 f"behind one another here; the throughput number is the "
                 f"overlapped pipeline)")


def _serving_section(lines: list[str], by_kind: dict) -> None:
    """Serving SLOs from the engine's typed ``serve`` records
    (serve/engine.py): per-request TTFT / queue wait / per-token latency
    percentiles over the completed requests, failures, and each engine
    run's summary line (policy, tokens/s, slot utilization, page-pool
    occupancy) — BENCH_serve writes one summary per policy, so the
    continuous-vs-static comparison reads directly off this section."""
    recs = by_kind.get("serve") or []
    sheds = by_kind.get("shed") or []
    brownouts = by_kind.get("brownout") or []
    if not recs and not sheds and not brownouts:
        return
    completed = [r for r in recs if r.get("event") == "completed"]
    failed = [r for r in recs if r.get("event") == "failed"]
    summaries = [r for r in recs if r.get("event") == "summary"]
    lines.append(f"== serving ({len(completed)} completed, "
                 f"{len(failed)} failed"
                 + (f", {len(sheds)} shed" if sheds else "") + ") ==")
    # Overload protection (docs/SERVING.md): typed sheds by reason and
    # the brownout ladder's travel — absent entirely on a run that
    # never shed (the common case stays terse).
    if sheds:
        by_reason: dict[str, int] = {}
        for r in sheds:
            by_reason[str(r.get("reason"))] = (
                by_reason.get(str(r.get("reason")), 0) + 1)
        lines.append("shed: " + ", ".join(
            f"{reason} {n}" for reason, n in sorted(by_reason.items())))
    if brownouts:
        max_level = max((r.get("level", 0) for r in brownouts), default=0)
        final = brownouts[-1].get("level")
        lines.append(
            f"brownout: {len(brownouts)} transitions, max level "
            f"{max_level}, final level {final} "
            f"({', '.join(brownouts[-1].get('applied') or []) or 'clear'})")
    breakers = by_kind.get("breaker") or []
    if breakers:
        opens = sum(1 for r in breakers if r.get("state") == "open")
        last: dict[str, str] = {}
        for r in breakers:
            last[str(r.get("replica"))] = str(r.get("state"))
        lines.append("breaker: " + f"{opens} opens   " + "  ".join(
            f"{k}={v}" for k, v in sorted(last.items())))
    # One percentile block PER POLICY: BENCH_serve writes both the
    # continuous and the static runs' per-request records onto one
    # stream, and a blended percentile would describe neither run.
    policies = sorted({str(r.get("policy")) for r in completed})
    for policy in policies:
        rows = [r for r in completed if str(r.get("policy")) == policy]
        prefix = f"[{policy}] " if len(policies) > 1 else ""
        for key, label in (("ttft_s", "TTFT"),
                           ("queue_wait_s", "queue wait"),
                           ("token_latency_s", "token latency")):
            vals = [r[key] for r in rows
                    if isinstance(r.get(key), (int, float))]
            if vals:
                lines.append(
                    f"{prefix}{label:14s} "
                    f"p50 {_fmt_s(percentile(vals, 50))}   "
                    f"p99 {_fmt_s(percentile(vals, 99))}   "
                    f"max {_fmt_s(max(vals))}")
    for s in summaries:
        occ = s.get("page_occupancy") or {}
        tps = s.get("tokens_per_s")
        util = s.get("slot_utilization")
        hit = s.get("cache_hit_rate")
        accept = s.get("draft_accept_rate")
        shed_n = s.get("requests_shed")
        lines.append(
            f"engine[{s.get('policy')}]: "
            f"{s.get('tokens_generated')} tokens"
            + (f" at {tps:,.1f} tokens/s" if isinstance(tps, (int, float))
               else "")
            + (f", slot utilization {util:.2f}"
               if isinstance(util, (int, float)) else "")
            + (f", page occupancy mean {occ.get('mean'):.2f} "
               f"max {occ.get('max'):.2f}"
               if isinstance(occ.get("mean"), (int, float)) else "")
            + (f", {shed_n} shed ({s.get('requests_rejected', 0)} "
               f"rejected)" if shed_n else ""))
        # Prefix-cache + speculative-decoding line only when either
        # lever was on (docs/SERVING.md) — a plain engine stays terse.
        if s.get("prefix_cache") or s.get("spec_k"):
            parts = []
            if s.get("prefix_cache"):
                parts.append(
                    f"cache hit {hit:.2f}"
                    if isinstance(hit, (int, float)) else "cache hit -")
                parts.append(f"{s.get('prefill_tokens_saved', 0)} prefill "
                             f"tokens saved")
                parts.append(f"{s.get('cached_prefix_pages', 0)} cached "
                             f"pages ({s.get('prefix_evictions', 0)} "
                             f"evicted)")
            if s.get("spec_k"):
                parts.append(
                    f"draft accept {accept:.2f} "
                    f"({s.get('draft_tokens_accepted', 0)}"
                    f"/{s.get('draft_tokens_proposed', 0)} at "
                    f"k={s.get('spec_k')})"
                    if isinstance(accept, (int, float))
                    else f"draft accept - (k={s.get('spec_k')})")
            lines.append("  " + ", ".join(parts))
    for r in failed:
        lines.append(f"  FAILED {r.get('request')}: {r.get('error')} "
                     f"({str(r.get('detail', ''))[:80]})")


def _fleet_serving_section(lines: list[str], by_kind: dict) -> None:
    """Multi-replica fleet serving (serve/fleet.py): router assignment
    counts from the typed ``router`` records, live migrations from the
    ``migration`` records, cell lifecycle events (typed ``cell``
    records, serve/cells.py) and the fleet summary's replica + per-cell
    tables — the post-mortem view of a replica- or cell-kill drill."""
    routed = by_kind.get("router") or []
    migs = by_kind.get("migration") or []
    fleet_sums = [r for r in by_kind.get("serve") or []
                  if r.get("event") == "summary"
                  and r.get("policy") == "fleet"]
    if not routed and not migs and not fleet_sums:
        return
    lines.append(f"== fleet serving ({len(routed)} routed, "
                 f"{len(migs)} migrated) ==")
    per: dict[str, int] = {}
    reasons: dict[str, int] = {}
    for r in routed:
        per[str(r.get("replica"))] = per.get(str(r.get("replica")), 0) + 1
        reasons[str(r.get("reason"))] = (
            reasons.get(str(r.get("reason")), 0) + 1)
    if per:
        lines.append("router: " + "  ".join(
            f"{name}={n}" for name, n in sorted(per.items()))
            + "   (" + ", ".join(f"{k} {v}"
                                 for k, v in sorted(reasons.items())) + ")")
    shown = migs[:12]
    for m in shown:
        lines.append(
            f"  migrated {m.get('request')}: {m.get('from_replica')} -> "
            f"{m.get('to_replica')} at {m.get('tokens_committed')} "
            f"committed tokens ({m.get('state')}, {m.get('pages')} pages, "
            f"round {m.get('round')})")
    if len(migs) > len(shown):
        lines.append(f"  ... and {len(migs) - len(shown)} more migrations")
    cell_recs = by_kind.get("cell") or []
    if cell_recs:
        ev: dict[str, int] = {}
        for c in cell_recs:
            ev[str(c.get("event"))] = ev.get(str(c.get("event")), 0) + 1
        lines.append("cell events: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(ev.items())))
    for s in fleet_sums:
        reps = s.get("replicas") or {}
        states = "  ".join(
            f"{name}={info.get('state')}"
            + (f"(x{info.get('kills')} kills)" if info.get("kills") else "")
            for name, info in sorted(reps.items()))
        lines.append(
            f"fleet: {s.get('live_replicas')}/{s.get('n_replicas')} "
            f"replicas live, {s.get('requests_migrated', 0)} requests "
            f"migrated over {s.get('migrations', 0)} moves, "
            f"{s.get('replica_kills', 0)} kills   {states}")
        cb = s.get("cells") or {}
        if cb:
            layout = cb.get("layout") or {}
            live = cb.get("live") or []
            extra = ""
            if cb.get("cell_kills"):
                extra += f", {cb['cell_kills']} cell kills"
            if cb.get("partitioned"):
                extra += f", partitioned {','.join(cb['partitioned'])}"
            lines.append(
                f"  cells: {len(live)}/{len(layout)} live ("
                + "  ".join(f"{c}[{len(m)}]"
                            for c, m in sorted(layout.items()))
                + ")" + extra)
        # Per-tenant SLO attainment from the fleet summary's metering
        # rollup (utils/metering.py): goodput fraction = in-deadline
        # tokens / tokens, next to the tenant's shed count.
        mt = s.get("metering") or {}
        for name, row in (mt.get("by_tenant") or {}).items():
            gf = row.get("goodput_fraction")
            lines.append(
                f"  tenant {name:<12} {row.get('requests', 0):>4} req   "
                f"goodput "
                + (f"{gf:6.1%}" if isinstance(gf, (int, float))
                   else "     -")
                + f"   sheds {row.get('sheds', 0)}   chip "
                  f"{row.get('chip_s', 0.0):.4f}s")


def _rtrace_summary(by_kind: dict) -> dict | None:
    """Fold the ``rtrace`` plane into the joined-timeline summary both
    report forms share: timeline/orphan counts, the terminal-event
    breakdown, linked migration hops, and fleet-wide per-phase seconds.
    None when the stream carries no request traces."""
    recs = by_kind.get("rtrace") or []
    if not recs:
        return None
    traces = join_request_traces(recs)
    terminals: dict[str, int] = {}
    phases: dict[str, float] = {}
    orphans = hops = 0
    for t in traces.values():
        if t["orphan"]:
            orphans += 1
        if t["terminal"]:
            terminals[t["terminal"]] = terminals.get(t["terminal"], 0) + 1
        hops += len(t["hops"])
        for p, s in t["phases"].items():
            phases[p] = phases.get(p, 0.0) + s
    return {
        "traces": len(traces),
        "orphans": orphans,
        "terminals": dict(sorted(terminals.items())),
        "migration_hops": hops,
        "phase_seconds": {p: round(s, 4)
                          for p, s in sorted(phases.items())},
    }


def _rtrace_section(lines: list[str], by_kind: dict) -> None:
    """Request-trace rollup (``rtrace`` records, utils/tracing.py):
    joined per-request timelines, terminal accounting and fleet-wide
    phase attribution. The zoomable per-request waterfall is
    ``scripts/dmp_xray.py``; this is the at-a-glance version."""
    s = _rtrace_summary(by_kind)
    if s is None:
        return
    lines.append(f"== request traces ({s['traces']} timelines) ==")
    terms = "  ".join(f"{k}={v}" for k, v in s["terminals"].items())
    lines.append(f"terminals: {terms or '(none)'}   orphans: "
                 f"{s['orphans']}   migration hops: {s['migration_hops']}")
    if s["phase_seconds"]:
        lines.append("phase seconds: " + "  ".join(
            f"{p}={v:.4f}s" for p, v in s["phase_seconds"].items()))
    lines.append("  (per-request waterfall: "
                 "python scripts/dmp_xray.py <stream> --worst 5)")


def _capacity_data(records: list[dict], by_kind: dict) -> dict | None:
    """Capacity observatory fold (serve/capacity.py over the ``meter``
    and ``utilization`` records, utils/metering.py). None when the
    stream carries no metering plane — training-only reports stay
    terse."""
    if not (by_kind.get("meter") or by_kind.get("utilization")):
        return None
    from distributed_model_parallel_tpu.serve.capacity import (
        build_capacity,
    )
    return build_capacity(records)


def _capacity_section(lines: list[str], records: list[dict],
                      by_kind: dict) -> None:
    """Fleet capacity rollup: billed cost per tenant, per-replica duty
    cycles, and sustainable-throughput headroom. The zoomable version
    (duty bars, what-if projections, billing-invariant gate) is
    ``scripts/dmp_capacity.py``."""
    cap = _capacity_data(records, by_kind)
    if cap is None:
        return
    lines.append(f"== capacity ({cap['meter_records']} meter records) ==")
    lines.append(
        f"observed {cap['tokens_per_s']:.1f} tok/s   sustainable "
        f"{cap['sustainable_tokens_per_s']:.1f} tok/s   headroom "
        f"{cap['headroom_tokens_per_s']:.1f} tok/s"
        + (f" ({cap['headroom_fraction']:.0%})"
           if cap.get("headroom_fraction") is not None else "")
        + f"   billed chip {cap['billed_chip_s']:.4f}s page "
          f"{cap['billed_page_s']:.4f}s   metering overhead "
          f"{cap['metering_overhead']['fraction']:.2%}")
    for name, row in cap["replicas"].items():
        duty = row["duty"]
        lines.append(
            f"  {name:<6} busy {duty['busy']:>4.0%}  stalled "
            f"{duty['stalled']:>4.0%}  brownout {duty['brownout']:>4.0%}  "
            f"idle {duty['idle']:>4.0%}  quarantined "
            f"{duty['quarantined']:>4.0%}  sustainable "
            f"{row['sustainable_tokens_per_s']:.1f} tok/s")
    for name, row in cap["tenants"].items():
        lines.append(
            f"  tenant {name:<12} {row['requests']:>4} req   chip "
            f"{row['chip_s']:.4f}s   page {row['page_s']:.4f}s   "
            f"{row['tokens']} tokens   {row['sheds']} sheds   "
            f"{row['hops']} hops")
    lines.append("  (observatory: python scripts/dmp_capacity.py "
                 "<stream> --what-if 2 --gate)")


def _plan_section(lines: list[str], by_kind: dict) -> None:
    """Parallelism-plan records (autotune/planner.emit_plan_record): which
    layout the autotuner chose, at which global step, and the nearest
    alternatives — so a re-planned elastic restart is auditable."""
    plans = by_kind.get("plan") or []
    if not plans:
        return
    lines.append(f"== parallelism plan ({len(plans)} planned) ==")
    for r in plans:
        axes = r.get("axes") or {}
        degrees = "x".join(f"{k}{v}" for k, v in axes.items()
                           if isinstance(v, (int, float)) and v > 1) or "dp1"
        cost = r.get("cost") or {}
        # "measured" only when a measurement actually succeeded —
        # error-only measured rows mean the analytic ranking stood.
        how = ("measured" if any("measured_s" in m
                                 for m in r.get("measured") or [])
               else "analytic")
        lines.append(
            f"  step {r.get('global_step', 0):>6}: "
            f"{r.get('strategy', '?')}[{degrees}] "
            f"M={r.get('num_microbatches', 1)} on "
            f"{r.get('n_devices', '?')} devices ({r.get('reason', '?')}, "
            f"{how}; {r.get('n_feasible', '?')} feasible / "
            f"{r.get('n_rejected', 0)} rejected)")
        if cost.get("total_s") is not None:
            lines.append(
                f"      predicted {_fmt_s(cost['total_s'])}/step "
                f"(compute {_fmt_s(cost.get('compute_s', 0))} x bubble "
                f"{cost.get('bubble', 1):.2f}, comm "
                f"{_fmt_s(cost.get('comm_s', 0))}, hidden "
                f"{_fmt_s(cost.get('comm_hidden_s', 0))})")
        # Alternatives = the analytic top minus the CHOSEN plan (which is
        # not necessarily top[0] — a measurement may have overruled it;
        # the model's preferred-but-rejected layout is then the most
        # interesting line here).
        chosen_key = (r.get("strategy"), axes, r.get("num_microbatches"))
        alts = [a for a in (r.get("top") or [])
                if (a.get("strategy"), a.get("axes"),
                    a.get("num_microbatches")) != chosen_key]
        for alt in alts[:3]:
            a = alt.get("axes") or {}
            ad = "x".join(f"{k}{v}" for k, v in a.items()
                          if isinstance(v, (int, float)) and v > 1) or "dp1"
            at = (alt.get("cost") or {}).get("total_s")
            lines.append(f"      alt {alt.get('strategy', '?')}[{ad}]"
                         + (f" {_fmt_s(at)}/step" if at else ""))


def _spans_section(lines: list[str], by_kind: dict) -> None:
    """Span-time rollup (``span`` records, utils/tracing.py): total and
    mean duration per span name — where the run's instrumented host time
    went. The zoomable view is ``scripts/dmp_trace.py``; this is the
    at-a-glance version."""
    spans = by_kind.get("span") or []
    if not spans:
        return
    totals: dict[str, list] = {}
    for r in spans:
        d = r.get("dur_s")
        if isinstance(d, (int, float)):
            totals.setdefault(str(r.get("name")), []).append(float(d))
    lines.append(f"== spans ({len(spans)} records, "
                 f"{len(totals)} names) ==")
    ranked = sorted(totals.items(), key=lambda kv: -sum(kv[1]))
    for name, ds in ranked[:12]:
        lines.append(f"  {name:20s} {_fmt_s(sum(ds)):>10s} total "
                     f"x{len(ds):<5d} mean {_fmt_s(sum(ds) / len(ds))}")
    lines.append("  (export the zoomable timeline: "
                 "python scripts/dmp_trace.py <stream> -o trace.json)")


def _gate_section(lines: list[str], by_kind: dict) -> None:
    """Regression-gate verdicts (``gate`` records, utils/baseline.py +
    scripts/dmp_gate.py): pass/fail per headline metric against the
    baseline ledger's noise band, with the span/phase attribution."""
    gates = by_kind.get("gate") or []
    if not gates:
        return
    r = gates[-1]
    regs = r.get("regressions") or []
    lines.append(f"== regression gate "
                 f"({'PASS' if r.get('ok') else 'REGRESSION'}, "
                 f"{len(r.get('verdicts') or [])} metrics checked vs "
                 f"{r.get('ledger')}) ==")
    for v in regs:
        lines.append(f"  REGRESSED {v.get('metric')}: {v.get('value')} vs "
                      f"baseline {v.get('baseline')} "
                      f"± {v.get('tolerance')}")
        attr = v.get("attribution") or {}
        where = attr.get("span") or attr.get("phase")
        if where:
            lines.append(f"      -> {where!r} grew "
                         f"{attr.get('baseline_share')} -> "
                         f"{attr.get('share')} of the run")
    for key in r.get("no_baseline") or []:
        lines.append(f"  (no baseline for {key} — first run of this key)")


def _comm_section(lines: list[str], by_kind: dict) -> None:
    snaps = by_kind.get("metrics") or []
    counters = snaps[-1].get("counters", {}) if snaps else {}
    comm = {k: v for k, v in counters.items()
            if k.startswith("collective_wire_bytes_est")}
    lines.append("== communication (trace-time estimates, per compile) ==")
    if not comm:
        lines.append("(no collective traffic recorded)")
    else:
        for key in sorted(comm):
            tags = key[key.index("{") + 1:-1]
            traces = counters.get(f"collective_traces{{{tags}}}", 0)
            ops = counters.get(f"collective_ops_est{{{tags}}}")
            # Message counts next to bytes: the alpha term of an
            # alpha-beta comm model (autotune/cost_model.py) — many small
            # collectives read differently from one big one here.
            ops_txt = f", {ops:.0f} msgs" if ops is not None else ""
            lines.append(f"{tags:40s} {_fmt_bytes(comm[key]):>12s} wire "
                         f"({traces:.0f} traces{ops_txt})")
    n_compiles = counters.get("jax_compiles")
    if n_compiles is not None:
        secs = counters.get("jax_compile_seconds", 0.0)
        lines.append(f"compilations: {n_compiles:.0f} "
                     f"({secs:.1f}s total backend compile time)")


def _memory_section(lines: list[str], by_kind: dict) -> None:
    mems = by_kind.get("memory") or []
    if not mems:
        return
    lines.append("== device memory ==")
    peak_by_dev: dict = {}
    for rec in mems:
        for d in rec.get("devices", []):
            cur = peak_by_dev.get(d.get("id"), 0)
            peak_by_dev[d.get("id")] = max(
                cur, d.get("peak_bytes_in_use", d.get("bytes_in_use", 0)))
    for dev_id, peak in sorted(peak_by_dev.items()):
        lines.append(f"device {dev_id}: peak {_fmt_bytes(peak)} in use")


def _resilience_section(lines: list[str], by_kind: dict,
                        t0: float | None = None) -> None:
    """Failure / recovery / divergence timeline: every detected failure
    (non-finite, stall, torn checkpoint, failed save, preemption, replica
    divergence) next to the recovery action the supervisor or consistency
    sentinel took (train/resilience.py, train/consistency.py), in event
    order. ``t0`` overrides the timeline origin (the fleet report passes
    the campaign start — a resumed tenant's stream holds several
    ``run_start`` records, and the last one would put earlier attempts'
    events at negative offsets)."""
    fails = by_kind.get("failure") or []
    recs = by_kind.get("recovery") or []
    cons = by_kind.get("consistency") or []
    resumes = by_kind.get("resume") or []
    if not fails and not recs and not cons and not resumes:
        return
    starts = by_kind.get("run_start") or []
    if t0 is None and starts:
        t0 = starts[-1].get("ts")
    if t0 is None:
        t0 = min((r.get("ts") for r in fails + recs + cons + resumes
                  if isinstance(r.get("ts"), (int, float))), default=0.0)
    header = f"== resilience ({len(fails)} failures, {len(recs)} recoveries"
    if cons:
        header += f", {len(cons)} consistency"
    if resumes:
        header += f", {len(resumes)} resumes"
    lines.append(header + ") ==")
    events = sorted(fails + recs + cons + resumes,
                    key=lambda r: r.get("ts") or 0.0)
    for r in events:
        dt = (r["ts"] - t0) if isinstance(r.get("ts"), (int, float)) else 0.0
        if r.get("kind") == "resume":
            extra = " ".join(
                f"{k}={r[k]}" for k in ("epoch", "batch_cursor",
                                        "global_step", "saved_mesh")
                if r.get(k) is not None)
            lines.append(f"  [+{dt:7.1f}s] resume    "
                         f"{str(r.get('slot')):<24}"
                         + (f" {extra}" if extra else ""))
        elif r.get("kind") == "consistency":
            extra = " ".join(
                f"{k}={r[k]}" for k in ("replicas", "groups", "outliers",
                                        "leaves", "check")
                if r.get(k) is not None)
            lines.append(f"  [+{dt:7.1f}s] consistency "
                         f"{str(r.get('status')):<22}"
                         + (f" {extra}" if extra else ""))
        elif r.get("kind") == "failure" or "error" in r:
            extra = " ".join(
                f"{k}={r[k]}" for k in ("epoch", "stage", "attempts",
                                        "retries_left")
                if r.get(k) is not None)
            detail = str(r.get("detail", ""))[:100]
            lines.append(f"  [+{dt:7.1f}s] failure   "
                         f"{str(r.get('error')):<24}"
                         + (f" {extra}" if extra else "")
                         + (f"  ({detail})" if detail else ""))
        else:
            extra = " ".join(
                f"{k}={r[k]}" for k in ("slot", "epoch", "retries_left",
                                        "lr_scale")
                if r.get(k) is not None)
            lines.append(f"  [+{dt:7.1f}s] recovery  "
                         f"{str(r.get('action')):<24}"
                         + (f" {extra}" if extra else ""))


def _trace_section(lines: list[str], trace_dir: str, top: int) -> None:
    from distributed_model_parallel_tpu.utils import xplane

    lines.append(f"== xplane trace ({trace_dir}) ==")
    try:
        xplane._pb2()
    except xplane.XplaneProtosUnavailable as e:
        lines.append(f"trace analysis skipped: {e}")
        return
    try:
        plane = xplane.device_plane(xplane.load_xspace(trace_dir))
    except (FileNotFoundError, ValueError) as e:
        lines.append(f"trace analysis skipped: {e}")
        return
    mods = xplane.module_events(plane)
    rows = xplane.exclude_envelopes(xplane.op_breakdown(plane))
    mod_s = sum(m.duration_ps for m in mods) / 1e12
    lines.append(f"{len(mods)} module executions, {mod_s:.4f}s device time")
    totals = xplane.category_totals(rows)
    for cat, sec in totals.items():
        lines.append(f"  {cat:24s} {sec * 1e3:10.2f} ms")
    # Comm/compute overlap from the measured device timeline: module wall
    # time vs summed op time. If collectives were fully serialized the
    # module wall ≈ compute + comm; fully hidden ≈ compute alone — so the
    # exposed share is the wall's excess over compute, capped at the comm
    # total. This is how "bucketed allreduce overlaps the backward" stops
    # being an assertion (reference Readme.md:148-157) and becomes a
    # number.
    comm_s = totals.get("allreduce", 0.0)
    if comm_s > 0 and mod_s > 0:
        compute_s = sum(totals.values()) - comm_s
        exposed = min(comm_s, max(0.0, mod_s - compute_s))
        lines.append(
            f"comm overlap: {comm_s * 1e3:.2f} ms collective device time, "
            f"{exposed * 1e3:.2f} ms exposed on the critical path → "
            f"comm-hidden fraction {1 - exposed / comm_s:.1%}")
    lines.append(f"top {top} ops:")
    for r in rows[:top]:
        lines.append(f"  {r.total_ps / 1e9:9.3f} ms x{r.count:6d} "
                     f"{r.category:18s} {r.name}")


def build_report(records: list[dict], *, trace_dir: str | None = None,
                 top: int = 15) -> str:
    """Render the report text for one telemetry stream."""
    by_kind = _by_kind(records)
    lines: list[str] = []

    starts = by_kind.get("run_start") or [{}]
    start = starts[-1]
    device = start.get("device", {}) or {}
    meta = start.get("meta", {}) or {}
    lines.append("== run ==")
    lines.append(
        f"run {start.get('run', '?')}   device "
        f"{device.get('platform', '?')} x{device.get('n_devices', '?')} "
        f"({device.get('device_kind', '?')})   jax {start.get('jax', '?')}")
    if meta:
        lines.append("meta " + " ".join(
            f"{k}={v}" for k, v in sorted(meta.items())
            if not isinstance(v, (dict, list))))
    for f in by_kind.get("failure", []):
        lines.append(f"FAILURE: {f.get('error')} — {f.get('detail', '')}")

    steps = by_kind.get("step", [])
    times = _steps_section(lines, steps)
    _mfu_section(lines, meta, device, by_kind, times)
    _phase_section(lines, by_kind)
    _serving_section(lines, by_kind)
    _fleet_serving_section(lines, by_kind)
    _capacity_section(lines, records, by_kind)
    _rtrace_section(lines, by_kind)
    _plan_section(lines, by_kind)
    _spans_section(lines, by_kind)
    _gate_section(lines, by_kind)
    _comm_section(lines, by_kind)
    _memory_section(lines, by_kind)
    _resilience_section(lines, by_kind)

    epochs = by_kind.get("epoch", [])
    if epochs:
        lines.append(f"== epochs ({len(epochs)}) ==")
        last = epochs[-1]
        keys = [k for k in ("epoch", "loss_train", "acc1_train", "loss_val",
                            "acc1_val", "time_per_batch", "tokens_per_s")
                if last.get(k) is not None]
        lines.append("last: " + "  ".join(
            f"{k}={last[k]:.4g}" if isinstance(last[k], float)
            else f"{k}={last[k]}" for k in keys))

    ends = by_kind.get("run_end")
    if ends:
        lines.append(f"run wall time: {ends[-1].get('wall_s', 0):.1f}s")
    else:
        lines.append("(no run_end record — run still in flight or killed)")

    if trace_dir:
        _trace_section(lines, trace_dir, top)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Machine-readable report (--json): the same answers as data, not text
# ---------------------------------------------------------------------------

def _pcts(vals: list[float]) -> dict | None:
    if not vals:
        return None
    return {"p50": percentile(vals, 50), "p90": percentile(vals, 90),
            "p99": percentile(vals, 99), "max": max(vals),
            "mean": sum(vals) / len(vals), "n": len(vals)}


def build_report_data(records: list[dict]) -> dict:
    """The report as one JSON-ready dict — sections as keys — so CI and
    the cockpit consume reports without screen-scraping. The section
    keys and the inner shapes of ``headline`` / ``resilience`` /
    ``serving`` / ``gate`` are a pinned schema
    (tests/test_report_json.py): additions are fine, renames and
    removals are breaking."""
    by_kind = _by_kind(records)
    start = (by_kind.get("run_start") or [{}])[-1]
    steps = by_kind.get("step") or []
    times = [r["step_time_s"] for r in steps
             if isinstance(r.get("step_time_s"), (int, float))]
    throughput = None
    for key, unit in (("tokens_per_s", "tokens/s"),
                      ("samples_per_s", "samples/s")):
        vals = [r[key] for r in steps
                if isinstance(r.get(key), (int, float))]
        if vals:
            throughput = {"unit": unit, "mean": sum(vals) / len(vals),
                          "max": max(vals)}
            break
    headline = {
        "n_steps": len(steps),
        "step_time_s": _pcts(times),
        "throughput": throughput,
    }
    resilience_events = sorted(
        (by_kind.get("failure") or []) + (by_kind.get("recovery") or [])
        + (by_kind.get("consistency") or []) + (by_kind.get("resume") or [])
        + (by_kind.get("fault") or []) + (by_kind.get("postmortem") or []),
        key=lambda r: r.get("ts") or 0.0)
    resilience = {
        "failures": len(by_kind.get("failure") or []),
        "recoveries": len(by_kind.get("recovery") or []),
        "consistency": len(by_kind.get("consistency") or []),
        "resumes": len(by_kind.get("resume") or []),
        "postmortems": [r.get("bundle")
                        for r in by_kind.get("postmortem") or []],
        "events": resilience_events,
    }
    serve = by_kind.get("serve") or []
    completed = [r for r in serve if r.get("event") == "completed"]
    policies: dict[str, dict] = {}
    for policy in sorted({str(r.get("policy")) for r in completed}):
        rows = [r for r in completed if str(r.get("policy")) == policy]
        policies[policy] = {
            key: _pcts([r[key] for r in rows
                        if isinstance(r.get(key), (int, float))])
            for key in ("ttft_s", "queue_wait_s", "token_latency_s")}
    serving = {
        "completed": len(completed),
        "failed": len([r for r in serve if r.get("event") == "failed"]),
        "policies": policies,
        "summaries": [r for r in serve if r.get("event") == "summary"],
        # Overload protection (docs/SERVING.md): the typed shed records
        # and brownout-ladder transitions, verbatim.
        "shed": by_kind.get("shed") or [],
        "brownout": by_kind.get("brownout") or [],
        "breaker": by_kind.get("breaker") or [],
    }
    gates = by_kind.get("gate") or []
    gate = None
    if gates:
        g = gates[-1]
        gate = {"ok": g.get("ok"),
                "regressions": g.get("regressions") or [],
                "verdicts": g.get("verdicts") or [],
                "no_baseline": g.get("no_baseline") or [],
                "ledger": g.get("ledger")}
    spans: dict[str, dict] = {}
    for r in by_kind.get("span") or []:
        d = r.get("dur_s")
        if isinstance(d, (int, float)):
            cell = spans.setdefault(str(r.get("name")),
                                    {"total_s": 0.0, "count": 0})
            cell["total_s"] += float(d)
            cell["count"] += 1
    alerts = by_kind.get("alert") or []
    snaps = by_kind.get("metrics") or []
    ends = by_kind.get("run_end") or []
    return {
        "run": {"run": start.get("run"), "device": start.get("device"),
                "jax": start.get("jax"), "meta": start.get("meta")},
        "headline": headline,
        "resilience": resilience,
        "serving": serving,
        "rtrace": _rtrace_summary(by_kind),
        "capacity": _capacity_data(records, by_kind),
        "gate": gate,
        "plan": by_kind.get("plan") or [],
        "spans": spans,
        "alerts": alerts,
        "counters": (snaps[-1].get("counters") or {}) if snaps else {},
        "epochs": {"count": len(by_kind.get("epoch") or []),
                   "last": (by_kind.get("epoch") or [None])[-1]},
        "wall_s": ends[-1].get("wall_s") if ends else None,
    }


def build_fleet_data(records: list[dict]) -> dict:
    """The fleet report as data: tenant table, fault ledger, health and
    alert timelines, unrecovered ledger."""
    tenants = sorted({r["tenant"] for r in records if r.get("tenant")})
    lifecycle = [r for r in records if r.get("kind") == "tenant"]
    out_tenants: dict[str, dict] = {}
    for tenant in tenants:
        recs = [r for r in records if r.get("tenant") == tenant]
        by_kind = _by_kind(recs)
        states = [r for r in lifecycle if r.get("name") == tenant]
        out_tenants[tenant] = {
            "state": states[-1].get("event") if states else None,
            "failures": len(by_kind.get("failure") or []),
            "recoveries": len(by_kind.get("recovery") or []),
            "resumes": len(by_kind.get("resume") or []),
            "epochs": len(by_kind.get("epoch") or []),
            "postmortems": [r.get("bundle")
                            for r in by_kind.get("postmortem") or []],
        }
    ledger = pair_faults(records)
    return {
        "tenants": out_tenants,
        "ledger": ledger,
        "unpaired": [r for r in ledger if not r["paired"]],
        "unrecovered": [{"name": r.get("name"), "error": r.get("error")}
                        for r in lifecycle if r.get("event") == "failed"],
        "health": [r for r in records if r.get("kind") == "health"],
        "alerts": [r for r in records if r.get("kind") == "alert"],
    }


# ---------------------------------------------------------------------------
# Fleet report: merged multi-tenant streams (orchestrator/ + dmp_soak.py)
# ---------------------------------------------------------------------------

# Which detection (failure error / consistency status) and recovery
# (recovery action / consistency status) records close the loop for each
# injected fault kind — the pairing the fault ledger audits. A fault is
# "paired" when a detection AND an action matching these sets appear in
# its tenant's stream after the injection.
FAULT_PAIRING: dict[str, tuple[frozenset, frozenset]] = {
    "nan_loss": (frozenset({"non-finite"}), frozenset({"restored"})),
    "nan_params": (frozenset({"non-finite"}), frozenset({"restored"})),
    "preempt": (frozenset({"preempted"}),
                frozenset({"checkpoint-and-exit"})),
    "stall": (frozenset({"stall"}), frozenset({"checkpoint-and-exit"})),
    "save_fail": (frozenset({"checkpoint-save-failed"}),
                  frozenset({"save-retried", "save-skipped"})),
    "tear_save": (frozenset({"checkpoint-torn"}),
                  frozenset({"checkpoint-fallback"})),
    # Silent corruption: detection is the sentinel's divergence (or, for
    # a consensus-poisoning drill, non-finite); the closing action is an
    # in-place replica re-broadcast, or a good-slot restore when there
    # was no quorum.
    "bitflip": (frozenset({"divergence", "non-finite"}),
                frozenset({"repaired", "replica-rebroadcast", "restored"})),
    "desync": (frozenset({"divergence", "non-finite"}),
               frozenset({"repaired", "replica-rebroadcast", "restored"})),
    "grad_skew": (frozenset({"divergence", "non-finite"}),
                  frozenset({"repaired", "replica-rebroadcast",
                             "restored"})),
}


def _detection_key(r: dict) -> str | None:
    if r.get("kind") == "failure":
        return r.get("error")
    if r.get("kind") == "consistency" and r.get("status") != "repaired":
        return r.get("status")
    return None


def _action_key(r: dict) -> str | None:
    if r.get("kind") == "recovery":
        return r.get("action")
    if r.get("kind") == "consistency" and r.get("status") == "repaired":
        return "repaired"
    return None


def pair_faults(records: list[dict]) -> list[dict]:
    """Pair every injected fault (typed ``fault`` record,
    train/resilience.py) with the detection and recovery that followed it
    in the same tenant's stream. Returns one ledger row per injection:
    ``{tenant, fault, site, detected, action, paired}``. Detections and
    actions are consumed in order, so two faults cannot claim the same
    recovery."""
    from distributed_model_parallel_tpu.utils.faults import (
        DEGRADATION_KINDS,
    )

    by_tenant: dict[str, list[dict]] = {}
    for r in records:
        by_tenant.setdefault(r.get("tenant") or "", []).append(r)
    ledger: list[dict] = []
    for tenant, recs in sorted(by_tenant.items()):
        used: set[int] = set()

        def _claim(start: int, match, accept: frozenset) -> tuple:
            for j in range(start, len(recs)):
                if j in used:
                    continue
                key = match(recs[j])
                if key is not None and key in accept:
                    used.add(j)
                    return j, key
            return len(recs), None

        for i, r in enumerate(recs):
            if r.get("kind") != "fault":
                continue
            kind = r.get("fault")
            if kind in DEGRADATION_KINDS:
                # Persistent degradations (slow_device/flaky_sync) are
                # not event faults with a detection/recovery pair — their
                # audit trail is the device-health timeline (quarantine,
                # migration, grow-back records), gated by the
                # degradation soak, not by this ledger.
                continue
            det_set, act_set = FAULT_PAIRING.get(
                kind, (frozenset(), frozenset()))
            dj, detected = _claim(i + 1, _detection_key, det_set)
            _, action = _claim(dj + 1 if detected else i + 1,
                               _action_key, act_set)
            ledger.append({
                "tenant": tenant, "fault": kind, "site": r.get("site"),
                "detected": detected, "action": action,
                "paired": detected is not None and action is not None,
            })
    return ledger


def _health_section(lines: list[str], records: list[dict],
                    t0: float) -> None:
    """Device-health timeline (utils/health.py): score transitions,
    quarantines and probation reinstates from the typed ``health``
    records, interleaved with the proactive migrations (tenant
    preemptions with reason ``device-degraded``) and grow-backs they
    caused — the self-healing story as one sequence."""
    health = [r for r in records if r.get("kind") == "health"]
    moves = [r for r in records if r.get("kind") == "tenant"
             and (str(r.get("reason", "")).startswith("device-degraded")
                  or str(r.get("reason", "")) == "grow-back"
                  or r.get("event") == "grow-back")]
    if not health and not moves:
        return
    n_q = sum(1 for r in health if r.get("event") == "quarantine")
    n_r = sum(1 for r in health if r.get("event") == "reinstate")
    lines.append(f"== device health ({len(health)} events, "
                 f"{n_q} quarantines, {n_r} reinstates) ==")
    for r in sorted(health + moves, key=lambda r: r.get("ts") or 0.0):
        dt = (r["ts"] - t0) if isinstance(r.get("ts"), (int, float)) else 0.0
        if r.get("kind") == "health":
            extra = " ".join(
                f"{k}={r[k]}" for k in ("signal", "score", "value",
                                        "baseline", "probation_ticks")
                if r.get(k) is not None)
            lines.append(f"  [+{dt:7.1f}s] {str(r.get('event')):<12} "
                         f"devices={r.get('devices')}"
                         + (f" {extra}" if extra else ""))
        elif r.get("event") == "grow-back":
            lines.append(f"  [+{dt:7.1f}s] grow-back    "
                         f"{r.get('name')}: {len(r.get('devices') or [])} "
                         f"-> {r.get('target_devices')} devices at step "
                         f"{r.get('global_step')}")
        else:
            lines.append(f"  [+{dt:7.1f}s] migration    "
                         f"{r.get('name')}: preempted off "
                         f"{r.get('devices') if r.get('devices') is not None else 'its slice'}"
                         f" ({r.get('reason')}) at step "
                         f"{r.get('global_step')}")


def build_fleet_report(records: list[dict]) -> str:
    """Render the fleet-level report for a merged multi-tenant record
    stream (utils/telemetry.merge_streams): the orchestration timeline,
    the device-health timeline (quarantines, migrations, grow-backs),
    one resilience timeline per tenant, per-tenant recovery/repair/resume
    counts, the injected-fault ledger, and the unrecovered-failure
    ledger."""
    lines: list[str] = []
    tenants = sorted({r["tenant"] for r in records if r.get("tenant")})
    lifecycle = [r for r in records if r.get("kind") == "tenant"]
    topology = [r for r in records if r.get("kind") == "event"
                and "topology" in str(r.get("message", ""))]
    lines.append(f"== fleet ({len(tenants)} tenants) ==")
    t0 = min((r.get("ts") for r in records
              if isinstance(r.get("ts"), (int, float))), default=0.0)
    for r in sorted(lifecycle + topology, key=lambda r: r.get("ts") or 0.0):
        dt = (r["ts"] - t0) if isinstance(r.get("ts"), (int, float)) else 0.0
        if r.get("kind") == "event":
            lines.append(f"  [+{dt:7.1f}s] {r.get('message')}")
        else:
            extra = " ".join(
                f"{k}={r[k]}" for k in ("devices", "global_step", "reason",
                                        "attempt", "error")
                if r.get(k) is not None)
            lines.append(f"  [+{dt:7.1f}s] {str(r.get('name')):<12} "
                         f"{str(r.get('event')):<20}"
                         + (f" {extra}" if extra else ""))

    _health_section(lines, records, t0)

    for tenant in tenants:
        recs = [r for r in records if r.get("tenant") == tenant]
        by_kind = _by_kind(recs)
        counts = {
            "failures": len(by_kind.get("failure") or []),
            "recoveries": len(by_kind.get("recovery") or []),
            "repairs": len([c for c in by_kind.get("consistency") or []
                            if c.get("status") == "repaired"]),
            "resumes": len(by_kind.get("resume") or []),
            "epochs": len(by_kind.get("epoch") or []),
        }
        lines.append(f"== tenant {tenant} ==")
        lines.append("  " + "  ".join(f"{k}={v}"
                                      for k, v in counts.items()))
        sub: list[str] = []
        _resilience_section(sub, by_kind, t0)
        lines += ["  " + s for s in sub]

    ledger = pair_faults(records)
    if ledger:
        lines.append(f"== fault ledger ({len(ledger)} injected) ==")
        for row in ledger:
            status = "ok" if row["paired"] else "UNPAIRED"
            lines.append(
                f"  {row['tenant']:<12} {row['fault']:<12} "
                f"detected={row['detected'] or '-':<24} "
                f"action={row['action'] or '-':<22} {status}")
    unpaired = [r for r in ledger if not r["paired"]]
    unrecovered = [r for r in lifecycle if r.get("event") == "failed"]
    lines.append(f"== unrecovered ({len(unrecovered)} tenant failures, "
                 f"{len(unpaired)} unpaired faults) ==")
    for r in unrecovered:
        lines.append(f"  {r.get('name')}: {r.get('error')}")
    for r in unpaired:
        lines.append(f"  {r['tenant']}: fault {r['fault']} never "
                     f"{'detected' if r['detected'] is None else 'recovered'}")
    if not unrecovered and not unpaired:
        lines.append("  (none — every injected fault was detected and "
                     "recovered, no tenant died)")
    return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="Render a run report from a telemetry JSONL stream")
    p.add_argument("jsonl", nargs="+",
                   help="telemetry stream(s) (RunLogger's "
                        "{log_dir}/{name}.jsonl or DMP_TELEMETRY); several "
                        "streams (or --fleet) render the merged "
                        "multi-tenant fleet report")
    p.add_argument("--fleet", action="store_true",
                   help="force the fleet report even for one stream "
                        "(e.g. just the orchestrator's fleet.jsonl)")
    p.add_argument("--trace", default=None,
                   help="xplane trace directory (utils/xplane.trace_to / "
                        "jax.profiler.start_trace) to join in")
    p.add_argument("--top", type=int, default=15,
                   help="top device ops to print from the trace")
    p.add_argument("--json", action="store_true",
                   help="emit the report as machine-readable JSON "
                        "(sections as keys; stable schema for the "
                        "headline/resilience/serving/gate sections) "
                        "instead of the text renderer")
    args = p.parse_args(argv)
    for path in args.jsonl:
        if not os.path.exists(path):
            raise SystemExit(f"no such telemetry file: {path}")
    if args.fleet or len(args.jsonl) > 1:
        from distributed_model_parallel_tpu.utils.telemetry import (
            merge_streams,
        )

        if args.trace:
            raise SystemExit("--trace joins a single-run report, not the "
                             "fleet view; render the tenant's own stream")
        records = merge_streams(args.jsonl)
        if not records:
            raise SystemExit("no parseable records in any stream")
        if args.json:
            import json

            print(json.dumps(build_fleet_data(records), indent=2,
                             default=str))
            return
        print(build_fleet_report(records))
        return
    records = read_records(args.jsonl[0])
    if not records:
        raise SystemExit(f"{args.jsonl[0]} holds no parseable records")
    if args.json:
        import json

        if args.trace:
            raise SystemExit("--trace joins the text report; the JSON "
                             "schema carries stream data only")
        print(json.dumps(build_report_data(records), indent=2,
                         default=str))
        return
    print(build_report(records, trace_dir=args.trace, top=args.top))


if __name__ == "__main__":
    main()
