#!/usr/bin/env python
"""LM generation CLI: restore a train_lm.py checkpoint and decode.

Single-host decoding routes through the serving engine (serve/ — the
continuous-batching paged-KV path, here in its one-request degenerate
case): the prompt prefills in fixed-size chunks against the paged cache,
so ANY prompt length runs the same two compiled programs and repeated CLI
calls hit jax's compile cache instead of re-jitting per prompt length
(the pre-engine CLI re-traced the whole decode for every distinct
prompt/gen shape). Greedy, temperature, top-k and nucleus (top-p)
sampling; sampled streams are per-request (seeded) and differ from the
pre-engine CLI's batch-keyed draws. Sharded decoding (--dp/--tp > 1) and
MoE checkpoints stay on models/transformer.generate — the engine is
replicated and rejects batch-coupled MoE routing.

Model-shape flags must match the training run; the checkpoint is read
from --checkpoint-dir (falling back to randomly initialized weights,
clearly announced, so the decode path can be exercised without a
training run).

Example:
  python scripts/train_lm.py --layers 2 --d-model 64 --steps 50
  python scripts/generate.py --layers 2 --d-model 64 \
      --prompt 5,17,42 --gen-steps 32 --temperature 0.8 --top-p 0.9
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from scripts._cpu_devices import force_cpu_devices

force_cpu_devices(("--dp", "--tp"))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dp", type=int, default=1,
                   help="batch-shard decoding over this many devices")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel decoding: heads (and the KV "
                        "cache) split over this many devices, the "
                        "training layout — no gather-to-one-device")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="prefill the prompt in N-token slices against the "
                        "growing KV cache (peak attention memory O(N*T) "
                        "instead of O(T0^2) — the long-prompt lever). On "
                        "the engine path this is the compiled chunk size "
                        "(default 32): prompts pad to a chunk multiple, so "
                        "every prompt length reuses one program")
    p.add_argument("--page-size", type=int, default=16,
                   help="KV-cache page size (tokens) on the engine path")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--max-seq-len", type=int, default=128)
    p.add_argument("--rope", action="store_true",
                   help="rotary positions; must match the training run")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query k/v heads; must match the training "
                        "run")
    p.add_argument("--attn-window", type=int, default=None,
                   help="sliding-window width; must match the training run")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="experts per block; must match the training run")
    p.add_argument("--moe-top-k", type=int, default=2,
                   help="routing fan-out; must match the training run "
                        "(shapes restore either way, but a mismatched k "
                        "routes differently than the trained model)")
    p.add_argument("--checkpoint-dir", default="./checkpoint")
    p.add_argument("--pp-stages", type=int, default=1,
                   help="stage count of the TRAINING run — only needed to "
                        "de-interleave a checkpoint trained with "
                        "--virtual-stages > 1 (decode itself runs "
                        "layer-stacked)")
    p.add_argument("--prompt", default="1,2,3",
                   help="comma-separated token ids (the LM trains on a "
                        "synthetic integer stream; there is no text "
                        "tokenizer)")
    p.add_argument("--gen-steps", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy argmax decoding")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()
    if args.moe_experts and not (1 <= args.moe_top_k <= args.moe_experts):
        raise SystemExit(
            f"--moe-top-k must be in [1, --moe-experts={args.moe_experts}]")
    if args.prefill_chunk is not None and args.prefill_chunk < 1:
        raise SystemExit(f"--prefill-chunk must be >= 1, got "
                         f"{args.prefill_chunk}")
    if args.page_size < 1:
        raise SystemExit(f"--page-size must be >= 1, got {args.page_size}")
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.train.checkpoint import Checkpointer

    cfg = tfm.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, d_ff=args.d_ff,
        max_seq_len=max(args.max_seq_len, 128),
        moe_experts=args.moe_experts, moe_top_k=args.moe_top_k,
        tp_axis="model" if args.tp > 1 else None,
        pos_embedding="rope" if args.rope else "learned",
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        attn_impl="flash" if args.attn_window is not None else "auto")
    params = tfm.init_params(jax.random.key(args.seed), cfg)

    ckpt = Checkpointer(args.checkpoint_dir)
    if ckpt.exists("lm"):
        # Restore only the params subtree of the LM checkpoint; shape flags
        # must match the training run.
        try:
            restored = ckpt.restore_subtree({"params": params}, "lm")
        except ValueError as e:
            raise SystemExit(
                f"checkpoint under {args.checkpoint_dir} does not match the "
                f"model flags (--layers/--d-model/... must equal the "
                f"training run's): {e}") from e
        params = restored["params"]
        # Orbax partial restore leaves abstract placeholders for target
        # leaves the checkpoint lacks (e.g. --kv-heads against a fused-
        # wqkv checkpoint) — catch that here instead of deep in jit.
        if any(isinstance(leaf, jax.ShapeDtypeStruct)
               for leaf in jax.tree.leaves(params)):
            raise SystemExit(
                f"checkpoint under {args.checkpoint_dir} does not match "
                f"the model flags (e.g. --kv-heads/--moe-experts change "
                f"the parameter tree); flags must equal the training "
                f"run's")
        # A 1f1b run with interleaved virtual stages checkpoints its block
        # rows in interleaved storage order (marker saved alongside) —
        # composing them in row order here would run a layer-permuted
        # model that generates garbage with no error. Convert back.
        try:
            v_marker = ckpt.restore_subtree(
                {"virtual_stages": jnp.zeros((), jnp.int32)}, "lm")
            ckpt_v = int(v_marker["virtual_stages"])
        except Exception:
            ckpt_v = 1                 # pre-marker checkpoint: always V=1
        if ckpt_v > 1:
            from distributed_model_parallel_tpu.parallel.spmd_pipeline import (
                deinterleave_block_rows,
            )

            if args.pp_stages < 2:
                raise SystemExit(
                    f"checkpoint was trained with virtual_stages={ckpt_v}; "
                    f"pass --pp-stages equal to the training stage count "
                    f"so the block rows can be de-interleaved")
            params["blocks"] = deinterleave_block_rows(
                params["blocks"], cfg.n_layers, args.pp_stages, ckpt_v)
            print(f"de-interleaved blocks (virtual_stages={ckpt_v}, "
                  f"S={args.pp_stages})", file=sys.stderr)
        print(f"restored LM checkpoint from {args.checkpoint_dir}",
              file=sys.stderr)
    else:
        print(f"no LM checkpoint under {args.checkpoint_dir}; using random "
              f"init (run scripts/train_lm.py first for a trained model)",
              file=sys.stderr)

    prompt_ids = [int(x) for x in args.prompt.split(",")]
    bad = [t for t in prompt_ids if not (0 <= t < cfg.vocab_size)]
    if bad:
        raise SystemExit(f"prompt tokens {bad} outside vocab [0, "
                         f"{cfg.vocab_size})")
    prompt = jnp.asarray([prompt_ids], jnp.int32)
    if args.dp > 1 or args.tp > 1:
        from distributed_model_parallel_tpu.config import MeshConfig
        from distributed_model_parallel_tpu.mesh import make_mesh

        if args.dp > 1:
            prompt = jnp.tile(prompt, (args.dp, 1))  # one row per replica
        spec = make_mesh(MeshConfig(data=args.dp, model=args.tp))
        out = tfm.generate_sharded(
            params, cfg, prompt, args.gen_steps, spec,
            rng=jax.random.key(args.seed + 1),
            temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p,
            prefill_chunk=args.prefill_chunk)
        tokens = [int(t) for t in out[0]]
    elif args.moe_experts:
        # MoE routing is batch-coupled (capacity drops depend on
        # co-resident tokens) — the engine rejects it; the single-batch
        # generate path stays correct for one request.
        print("MoE checkpoint: decoding via models.transformer.generate "
              "(the serving engine rejects batch-coupled MoE routing)",
              file=sys.stderr)
        out = tfm.generate(params, cfg, prompt, args.gen_steps,
                           rng=jax.random.key(args.seed + 1),
                           temperature=args.temperature,
                           top_k=args.top_k, top_p=args.top_p,
                           prefill_chunk=args.prefill_chunk)
        tokens = [int(t) for t in out[0]]
    else:
        # Engine path (single-request degenerate case of continuous
        # batching): fixed prefill chunk + fixed decode program, so any
        # prompt length — and any later CLI call against the same model
        # shape — reuses the same two compiled programs.
        from distributed_model_parallel_tpu.serve import (
            Engine,
            ServeConfig,
        )

        chunk = args.prefill_chunk if args.prefill_chunk else 32
        serve = ServeConfig(
            n_slots=1, page_size=args.page_size,
            n_pages=-(-cfg.max_seq_len // args.page_size) + 1,
            max_seq_len=cfg.max_seq_len,
            prefill_chunk=min(chunk, cfg.max_seq_len),
            temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p)
        engine = Engine(params, cfg, serve)
        print(f"engine decode: paged KV (page={serve.page_size}, "
              f"pool={serve.n_pages} pages), prefill chunk "
              f"{serve.prefill_chunk} — prompt lengths bucket to one "
              f"compiled program", file=sys.stderr)
        req = engine.submit(prompt_ids, args.gen_steps,
                            seed=args.seed + 1)
        engine.run()
        if req.error:
            raise SystemExit(f"engine failed: {req.error}")
        tokens = prompt_ids + req.generated
    print(",".join(str(t) for t in tokens))


if __name__ == "__main__":
    main()
