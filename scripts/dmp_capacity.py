#!/usr/bin/env python
"""dmp_capacity — fleet capacity observatory over metering streams.

Reads the typed ``meter`` / ``utilization`` / ``rtrace`` / ``serve``
records a metered serving run emits (utils/metering.py,
serve/capacity.py) and renders:

* the per-tenant cost table — chip-seconds, page-seconds, residency,
  tokens, sheds and migration hops billed to each tenant;
* the per-replica utilization timeline — each replica's duty cycle
  (busy / stalled / brownout / idle / quarantined) as a bar, with its
  observed, sustainable and headroom tokens/s;
* ``--what-if N`` — project fleet capacity at replicas ± N, pricing
  dispatch-launch overhead with the autotune cost model's ``alpha_s``;
* ``--gate`` — enforce the billing invariants (exit non-zero on any):
  duty buckets partition each replica's wall within 1%, billed
  chip-seconds never exceed the fleet's iterated wall, and every
  terminal rtrace pairs 1:1 with a terminal meter record.

Usage:
    python scripts/dmp_capacity.py /tmp/run/serve.jsonl
    python scripts/dmp_capacity.py a.jsonl b.jsonl --what-if -2 --what-if 2
    python scripts/dmp_capacity.py serve.jsonl --gate --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.serve.capacity import (  # noqa: E402
    build_capacity,
    check_invariants,
    what_if,
)
from distributed_model_parallel_tpu.utils.metering import (  # noqa: E402
    LEDGER_BUCKETS,
)
from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    read_records,
)

# One glyph per duty bucket, in LEDGER_BUCKETS order: busy, stalled,
# brownout, idle, quarantined.
_BAR_GLYPHS = {"busy": "#", "stalled": "~", "brownout": "!",
               "idle": ".", "quarantined": "x"}


def load_records(paths: list[str]) -> list[dict]:
    records: list[dict] = []
    for path in paths:
        records.extend(read_records(path))
    return records


def duty_bar(duty: dict, width: int = 24) -> str:
    """Fixed-width duty-cycle bar: each bucket's glyph run sized by its
    fraction (largest-remainder rounding keeps the bar exactly
    ``width`` wide)."""
    cells = []
    acc = 0
    for i, b in enumerate(LEDGER_BUCKETS):
        n = (width - acc if i == len(LEDGER_BUCKETS) - 1
             else int(round(duty.get(b, 0.0) * width)))
        n = max(0, min(n, width - acc))
        cells.append(_BAR_GLYPHS[b] * n)
        acc += n
    return "".join(cells).ljust(width, _BAR_GLYPHS["idle"])[:width]


def render(cap: dict, out) -> None:
    print("== capacity ==", file=out)
    print(f"wall: {cap['wall_s']:.3f}s  replicas: {cap['n_replicas']}"
          + (f" (live {cap['live_replicas']})"
             if cap.get("live_replicas") is not None else "")
          + f"  tokens: {cap['tokens']}  observed: "
            f"{cap['tokens_per_s']:.1f} tok/s  sustainable: "
            f"{cap['sustainable_tokens_per_s']:.1f} tok/s  headroom: "
            f"{cap['headroom_tokens_per_s']:.1f} tok/s"
          + (f" ({cap['headroom_fraction']:.0%})"
             if cap.get("headroom_fraction") is not None else ""),
          file=out)
    print(f"billed: chip {cap['billed_chip_s']:.4f}s  page "
          f"{cap['billed_page_s']:.4f}s  meter records: "
          f"{cap['meter_records']}  metering overhead: "
          f"{cap['metering_overhead']['fraction']:.2%} of iteration "
          f"wall", file=out)
    if cap["tenants"]:
        print("-- per-tenant cost --", file=out)
        print(f"  {'tenant':<14} {'requests':>8} {'chip_s':>10} "
              f"{'page_s':>10} {'tokens':>8} {'sheds':>6} {'hops':>5}",
              file=out)
        for name, row in cap["tenants"].items():
            print(f"  {name:<14} {row['requests']:>8} "
                  f"{row['chip_s']:>10.4f} {row['page_s']:>10.4f} "
                  f"{row['tokens']:>8} {row['sheds']:>6} "
                  f"{row['hops']:>5}", file=out)
    if cap["replicas"]:
        print("-- utilization timeline (#busy ~stalled !brownout "
              ".idle xquarantined) --", file=out)
        for name, row in cap["replicas"].items():
            cell = f" cell={row['cell']}" if row.get("cell") else ""
            print(f"  {name:<6} [{duty_bar(row['duty'])}] "
                  f"busy={row['duty']['busy']:.0%}"
                  f" obs={row['tokens_per_s']:.1f}"
                  f" sust={row['sustainable_tokens_per_s']:.1f}"
                  f" headroom={row['headroom_tokens_per_s']:.1f}"
                  f" tok/s{cell}", file=out)


def render_what_if(proj: dict, out) -> None:
    sat = "  SATURATED" if proj["saturated"] else ""
    print(f"what-if {proj['delta']:+d} -> {proj['replicas']} replicas: "
          f"capacity {proj['capacity_tokens_per_s']:.1f} tok/s, "
          f"offered {proj['offered_tokens_per_s']:.1f} tok/s"
          + (f", projected utilization "
             f"{proj['projected_utilization']:.0%}"
             if proj.get("projected_utilization") is not None else "")
          + f", headroom {proj['headroom_tokens_per_s']:.1f} tok/s"
          + sat, file=out)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dmp_capacity",
        description="Fleet capacity observatory over metering streams.")
    p.add_argument("streams", nargs="+",
                   help="telemetry stream path(s) (.jsonl; rotated "
                        "parts fold in automatically)")
    p.add_argument("--what-if", type=int, action="append", default=None,
                   metavar="N", dest="what_if",
                   help="project capacity at replicas +/- N "
                        "(repeatable)")
    p.add_argument("--json", action="store_true",
                   help="emit JSON instead of text")
    p.add_argument("--gate", action="store_true",
                   help="exit non-zero when a billing invariant fails")
    p.add_argument("--gate-tolerance", type=float, default=0.01,
                   help="relative tolerance for the partition and "
                        "chip-bound invariants (default: 0.01)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    records = load_records(args.streams)
    cap = build_capacity(records)
    out = sys.stdout

    projections = [what_if(cap, d) for d in (args.what_if or ())]
    failures: list[str] = []
    rc = 0
    if args.gate:
        failures = check_invariants(records,
                                    tolerance=args.gate_tolerance)
        if not any(r.get("kind") == "meter" for r in records):
            failures.append("no meter records found (metering off, or "
                            "not a serving stream)")
        rc = 1 if failures else 0

    if args.json:
        payload = {"capacity": cap}
        if projections:
            payload["what_if"] = projections
        if args.gate:
            payload["gate_failures"] = failures
        json.dump(payload, out, default=str)
        print(file=out)
        return rc

    render(cap, out)
    for proj in projections:
        render_what_if(proj, out)
    if args.gate:
        for f in failures:
            print(f"GATE FAIL: {f}", file=out)
        if not failures:
            print(f"GATE OK: {cap['meter_records']} meter records "
                  f"billed {cap['billed_chip_s']:.4f} chip-seconds "
                  f"within the iterated wall; duty buckets partition "
                  f"every replica's wall within "
                  f"{args.gate_tolerance:.0%}", file=out)
    return rc


if __name__ == "__main__":
    sys.exit(main())
