#!/usr/bin/env python
"""Parallelism-plan CLI: rank mesh layouts for a workload, optionally
validate the top candidates with short measured steps.

One command over the autotuner core (``autotune/``, docs/AUTOTUNE.md):

* ``--dry-run`` — pure analytic planning (enumerate -> HBM filter ->
  alpha-beta rank), no device programs built. Prints ONE JSON object:
  the chosen plan, the ranked feasible list, and the rejections. Exits
  nonzero (rc 2) with a parseable ``{"error": "no-feasible-plan", ...}``
  record when the constraints admit no layout — the CI smoke pins both
  contracts (tests/test_autotune.py).
* ``--measure K`` — additionally time the analytic top-K candidates with
  short real steps through **bench.py's shared workload builders**
  (``build_lm_bench`` with per-plan mesh overrides), letting the
  measurement overrule the model. Needs the devices to actually exist
  (``--devices`` spawns virtual CPU devices via scripts/_cpu_devices.py
  when JAX_PLATFORMS=cpu).

Examples:
  JAX_PLATFORMS=cpu python scripts/dmp_plan.py --workload lm --devices 8 \\
      --batch 16 --seq 128 --dry-run
  JAX_PLATFORMS=cpu python scripts/dmp_plan.py --workload lm --devices 8 \\
      --batch 16 --seq 128 --d-model 64 --measure 3
  python scripts/dmp_plan.py --workload cnn --model mobilenetv2 \\
      --devices 8 --batch 512 --dry-run
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts._cpu_devices import force_cpu_devices  # noqa: E402

force_cpu_devices(("--devices",))


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workload", choices=("lm", "cnn"), default="lm")
    p.add_argument("--devices", type=int, default=8,
                   help="device count to plan for (analytic planning is "
                        "pure math; --measure needs them to exist)")
    p.add_argument("--batch", type=int, default=64)
    # LM model geometry (tiny-by-default so the dryrun is CPU-cheap but
    # compute-dominant enough that the bubble/overlap terms matter).
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--moe-experts", type=int, default=0)
    # CNN workload.
    p.add_argument("--model", default="tinycnn",
                   help="CNN model registry key (--workload cnn)")
    p.add_argument("--image-size", type=int, default=32)
    # Planner knobs.
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM override, GB (default: "
                        "backend-reported / device-kind table / unfiltered)")
    p.add_argument("--top", type=int, default=None,
                   help="truncate the printed ranked list (default: all)")
    p.add_argument("--dry-run", action="store_true",
                   help="analytic only — no device programs built")
    p.add_argument("--measure", type=int, default=0, metavar="K",
                   help="time the analytic top-K through bench.py's "
                        "builders; measured-best wins")
    p.add_argument("--measure-steps", type=int, default=2)
    return p.parse_args(argv)


def _build_workload(args):
    from distributed_model_parallel_tpu.autotune import search

    if args.workload == "lm":
        from distributed_model_parallel_tpu.models import transformer as tfm

        model = tfm.TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.heads, n_layers=args.layers, d_ff=args.d_ff,
            max_seq_len=args.seq, pos_embedding="rope",
            moe_experts=args.moe_experts)
        return search.lm_workload(model, args.batch, args.seq), model
    from distributed_model_parallel_tpu.config import DataConfig, ModelConfig

    model_cfg = ModelConfig(name=args.model)
    data_cfg = DataConfig(name="synthetic", batch_size=args.batch,
                          image_size=args.image_size)
    return search.cnn_workload(model_cfg, data_cfg), model_cfg


def _lm_measure_fn(args, model_cfg):
    """Per-plan measured seconds/step through bench.build_lm_bench — the
    planner's measured validation rides the SAME builder the BENCH_lm
    artifacts come from (module docstring)."""
    import bench
    from distributed_model_parallel_tpu.autotune import (
        lm_model_for_plan,
        mesh_from_plan,
        time_step_fn,
    )

    def measure(plan):
        _, step, _ = bench.build_lm_bench(
            mesh=mesh_from_plan(plan), model=lm_model_for_plan(model_cfg,
                                                               plan),
            batch=args.batch, seq=args.seq, steps=args.measure_steps,
            num_microbatches=plan.num_microbatches)
        return time_step_fn(step, warmup=1, iters=args.measure_steps)

    return measure


def main(argv=None) -> None:
    args = parse_args(argv)
    from distributed_model_parallel_tpu.autotune import (
        InfeasiblePlanError,
        memory,
        planner,
    )

    hbm = (args.hbm_gb * 1e9 if args.hbm_gb is not None
           else memory.device_hbm_bytes())
    workload, model_cfg = _build_workload(args)
    measure_fn = None
    if args.measure > 0 and args.dry_run:
        raise SystemExit(
            "--measure times candidates with real device steps, which "
            "--dry-run promises not to run; pick one — no silent ignores")
    if args.measure > 0:
        if args.workload != "lm":
            raise SystemExit(
                "--measure currently drives bench.build_lm_bench; use "
                "--workload lm (the cnn path ranks analytically)")
        import jax

        if len(jax.devices()) < args.devices:
            raise SystemExit(
                f"--measure needs {args.devices} live devices, have "
                f"{len(jax.devices())} (on CPU, pass --devices before "
                f"jax initializes — scripts/_cpu_devices.py)")
        measure_fn = _lm_measure_fn(args, model_cfg)
    try:
        decision = planner.plan_parallelism(
            workload, args.devices, hbm_bytes=hbm,
            measure_fn=measure_fn, measure_top=args.measure)
    except InfeasiblePlanError as e:
        print(json.dumps({"error": "no-feasible-plan",
                          "workload": args.workload,
                          "n_devices": args.devices,
                          "detail": str(e)}))
        sys.exit(2)
    out = decision.telemetry_payload()
    ranked = [r.payload() for r in decision.ranked]
    out["ranked"] = ranked[:args.top] if args.top else ranked
    out["rejected"] = [{**p.payload(), "reason": why}
                       for p, why in decision.rejected]
    print(json.dumps(out))
    print(f"[dmp_plan] {decision.describe()}", file=sys.stderr)


if __name__ == "__main__":
    main()
