#!/usr/bin/env python
"""Chaos-soak campaign: the whole robustness stack under fleet traffic.

``dmp_chaos.py`` drills one trainer, one fault, one scenario at a time.
This driver grows that into the production scenario the stack was built
for: a multi-tenant orchestrator (``distributed_model_parallel_tpu/
orchestrator/``) runs several concurrent heterogeneous jobs — CNN
(``train/trainer.py``), LM and MoE (``train/lm_trainer.py``), pipeline
(``train/pipeline_trainer.py``) — on a shared device pool while a seeded
schedule injects faults (``utils/faults.py``, corruption drills
included), preempts by priority, shrinks and regrows the topology, and
churns tenants. Every cross-feature interaction the single-trainer
drills cannot reach — a preemption landing while a sentinel repair is
one cadence away, two tenants racing for freed devices, an emergency
checkpoint resharded onto a shrunken slice — happens here on purpose.

Modes:

* ``fast`` (default) — one deterministic campaign: fixed seed, tiny
  models, CPU-friendly, seconds-to-a-minute; the ``chaos`` pytest tier
  runs it on every CI pass (tests/test_soak.py).
* ``long`` — repeated campaigns with derived seeds until
  ``--duration-s`` wall clock is spent (hours for a real soak; a tiny
  budget still runs one full campaign — the CI-bounded smoke); each
  campaign is the fast campaign's shape scaled by ``--tenants`` /
  ``--epochs``.

Scenarios (``--scenario``): ``chaos`` (the campaign above);
``degradation`` — the device-health drill (utils/health.py): an
injected ``slow_device`` ramp must get its slice quarantined, its
tenant proactively migrated through the preempt-checkpoint path
(dp4 -> dp2), and grown back to the requested dp at the exact global
step after probation, with a sub-threshold ``flaky_sync`` bystander as
the false-positive control (see ``run_degradation_campaign``);
``overload`` and ``xray`` — the serving-fleet overload and
request-tracing drills; and the FLEET scenarios ``failover`` /
``flashcrowd`` / ``flood`` / ``diurnal`` — seeded production traffic
(serve/traffic.py) replayed on a virtual clock through an N-replica
multi-cell serving fleet (``--replicas`` / ``--cells``) while a
cell-scale correlated fault (``kill_cell`` / ``slow_cell`` /
``partition``, utils/faults.py) hits one cell, gated on zero lost
requests, bitwise token parity, complete rtrace timelines, goodput
within ``--goodput-band`` of the clean run, and exact-slice cell
grow-back (see ``run_fleet_scenario``); and ``crashrecovery`` — the
write-ahead-journal crash-consistency drill: a hard replica crash (no
drain) and a full fleet restart (torn journal tail included) must both
recover every accepted request bitwise at its committed-token watermark
with exactly-once terminal accounting, a replay-deterministic schedule
digest, < 3% journal overhead and zero journal-off behavior change
(see ``run_crashrecovery_scenario``). Any scenario's gate violation
dumps a flight-recorder postmortem bundle and prints its path before
the nonzero exit.

Every campaign gates on the same four invariants and exits non-zero when
any fails:

1. zero unrecovered failures (no tenant ends FAILED);
2. every preempted tenant resumed at its EXACT global step;
3. every injected fault is paired with its detection + recovery/repair/
   resume record in the merged telemetry (``dmp_report.pair_faults``);
4. every tenant completed its configured epochs.

The fault pool spans the ``utils/faults.py`` taxonomy: nan_loss,
nan_params, preempt, stall (escalating to checkpoint-and-exit),
save_fail, tear_save (always scheduled together with a later nan so a
restore provably walks past the torn version), and the corruption drills
bitflip / desync / grad_skew on replicated-dp tenants. Corruption kinds
are only assigned to tenants whose minimum slice keeps >= 2 replicas —
the same topology rule the trainers enforce loudly.

One fleet-level report is rendered from the merged tenant streams
(``utils/telemetry.merge_streams`` + ``dmp_report.build_fleet_report``),
followed by ONE parseable JSON summary line.

Usage:
  JAX_PLATFORMS=cpu python scripts/dmp_soak.py [--seed 0] [--mode fast]
      [--tenants 4] [--epochs 2] [--quantum 2] [--no-churn] [--no-shrink]
  JAX_PLATFORMS=cpu python scripts/dmp_soak.py --mode long --duration-s 3600
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Virtual CPU devices (must precede any jax import; no-op when the test
# session already forced a device count).
if (os.environ.get("JAX_PLATFORMS") == "cpu"
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", default="fast", choices=["fast", "long"])
    p.add_argument("--scenario", default="chaos",
                   choices=["chaos", "degradation", "overload", "xray",
                            "failover", "flashcrowd", "flood", "diurnal",
                            "crashrecovery"],
                   help="chaos: the heterogeneous fault campaign; "
                        "degradation: the device-health drill — an "
                        "injected slow_device straggler must be "
                        "quarantined and its tenant migrated (shrunk "
                        "dp4->dp2) and grown back to its requested dp at "
                        "the exact global step (utils/health.py); "
                        "overload: the serving-fleet overload drill — "
                        "2x offered load must hold goodput within "
                        "--goodput-band of clean capacity via typed "
                        "shedding, bounded queues and a brownout that "
                        "fires and resolves, with completed tokens "
                        "bitwise identical to the clean run "
                        "(serve/overload.py); "
                        "xray: the request-tracing drill — a replica "
                        "kill under live traffic must reconstruct a "
                        "complete causally ordered rtrace timeline for "
                        "every admitted request, with migration hops "
                        "linked across the source/destination streams "
                        "and zero orphan spans (scripts/dmp_xray.py); "
                        "failover / flashcrowd / flood / diurnal: the "
                        "cell-scale correlated-failure drills — seeded "
                        "production traffic (serve/traffic.py) replayed "
                        "on a virtual clock through an N-replica, "
                        "multi-cell serving fleet while a correlated "
                        "fault (kill_cell / slow_cell / partition — "
                        "utils/faults.py) hits one cell, gated on zero "
                        "lost requests, bitwise token parity, complete "
                        "rtrace timelines, goodput >= --goodput-band of "
                        "the clean run and (failover) exact-slice cell "
                        "grow-back (see run_fleet_scenario); "
                        "crashrecovery: the crash-consistency drill — "
                        "the write-ahead request journal "
                        "(serve/journal.py) must recover BOTH a hard "
                        "replica crash (engine discarded, no drain) and "
                        "a full fleet restart (a torn journal tail "
                        "included) with bitwise token parity vs an "
                        "uninterrupted reference, exactly one terminal "
                        "per trace, a replay-deterministic schedule "
                        "digest, < 3%% journal write overhead and a "
                        "journal-off run whose schedule digest is "
                        "byte-identical to the journal-on run "
                        "(see run_crashrecovery_scenario)")
    p.add_argument("--goodput-band", default=0.8, type=float,
                   help="overload/fleet scenarios: goodput under the "
                        "event must stay >= this fraction of clean-run "
                        "capacity")
    p.add_argument("--replicas", default=16, type=int,
                   help="fleet scenarios: serving replicas (>= --cells; "
                        "the headline drill runs 16)")
    p.add_argument("--cells", default=4, type=int,
                   help="fleet scenarios: cells the replicas partition "
                        "into (>= 2 — failover needs a surviving cell)")
    p.add_argument("--seed", default=0, type=int,
                   help="campaign seed: fault kinds/sites, priorities and "
                        "event rounds all derive from it — same seed, "
                        "same campaign")
    p.add_argument("--tenants", default=4, type=int,
                   help="initial tenant count (>= 3; workloads cycle "
                        "cnn / lm / pipeline / moe)")
    p.add_argument("--epochs", default=2, type=int,
                   help="epochs per tenant")
    p.add_argument("--quantum", default=2, type=int,
                   help="train steps granted per tenant per round")
    p.add_argument("--duration-s", default=3600.0, type=float,
                   help="long mode: wall-clock budget across campaigns")
    p.add_argument("--no-churn", action="store_true",
                   help="skip the mid-campaign high-priority tenant "
                        "submission (the churn + priority-preemption event)")
    p.add_argument("--no-shrink", action="store_true",
                   help="skip the topology shrink/grow events")
    p.add_argument("--workdir", default=None,
                   help="campaign root (default: a fresh tmp dir)")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# tenant recipes (sized for the fast tier; long mode reuses them — the
# soak's scale comes from tenant count x campaign count, not model size)
# ---------------------------------------------------------------------------

def _cnn_config(workdir, name, dp, epochs, **kw):
    from distributed_model_parallel_tpu.config import (
        DataConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        TrainConfig,
    )

    defaults = dict(
        model=ModelConfig(name="tinycnn"),
        data=DataConfig(name="synthetic", batch_size=16, eval_batch_size=16,
                        synthetic_train_size=48, synthetic_eval_size=16),
        optimizer=OptimizerConfig(learning_rate=0.1, warmup_steps=2),
        mesh=MeshConfig(data=dp), epochs=epochs,
        # Eval every epoch costs real wall clock on a 1-core host and the
        # campaign gates on resilience, not accuracy.
        eval_every=100,
        log_dir=os.path.join(workdir, name, "log"),
        checkpoint_dir=os.path.join(workdir, name, "ckpt"),
        log_name=name, log_every_n_steps=1000,
    )
    defaults.update(kw)
    return TrainConfig(**defaults)


def _lm_config(workdir, name, dp, epochs, *, moe=0, **kw):
    from distributed_model_parallel_tpu.config import MeshConfig
    from distributed_model_parallel_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_model_parallel_tpu.train.lm_trainer import LMTrainConfig

    defaults = dict(
        model=TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_seq_len=16,
                                moe_experts=moe,
                                moe_top_k=2 if moe else 1),
        mesh=MeshConfig(data=dp), batch_size=4, seq_len=16,
        steps_per_epoch=3, epochs=epochs, n_tokens=2000, eval_batches=0,
        log_dir=os.path.join(workdir, name, "log"),
        checkpoint_dir=os.path.join(workdir, name, "ckpt"),
        log_name=name,
    )
    defaults.update(kw)
    return LMTrainConfig(**defaults)


# Per-workload fault-plan templates: (plan, extra config kw). Step
# indexes assume >= 6 steps of budget (epochs >= 2 x 3 steps). Recovery
# knobs ride along so every injected fault has an armed detector and a
# recovery policy — the same no-undetectable-faults rule the supervisor
# enforces at construction.
def _fault_menu(steps_per_epoch: int, epochs: int):
    from distributed_model_parallel_tpu.config import RecoveryConfig

    total = steps_per_epoch * epochs
    mid = max(1, total // 2)

    def rec(faults, **kw):
        return RecoveryConfig(max_retries=3, lr_shrink=0.5,
                              faults=tuple(faults), **kw)

    # (label, needs_replicas, config kwargs)
    return [
        ("nan_loss", False,
         dict(recovery=rec([f"nan_loss@{mid}"]), check_finite_every=1)),
        ("nan_params", False,
         dict(recovery=rec([f"nan_params@{mid}"]), check_finite_every=1)),
        ("preempt", False,
         dict(recovery=rec([f"preempt@{mid}"]))),
        ("stall", False,
         dict(recovery=rec(["stall@1:0.3"], stall_exit=True),
              stall_budget_s=0.05)),
        ("save_fail", False,
         # save site occurrence 0 is the supervisor's begin() good-slot
         # save — the one save whose failure is handled (retried) rather
         # than raised.
         dict(recovery=rec(["save_fail@0"], ), check_finite_every=1)),
        ("tear_save", False,
         # Deterministic pairing: tear the SECOND save (epoch 0's
         # note_good — eval is off so no best-acc save interleaves, and
         # this template is restricted to the cnn/pipeline trainers,
         # whose only per-epoch save IS note_good), then a final-epoch
         # NaN forces a good-slot restore that must walk past the torn
         # version — checkpoint-torn + checkpoint-fallback + restored,
         # all on one tenant.
         dict(recovery=rec(["tear_save@1",
                            f"nan_loss@{steps_per_epoch + 1}"]),
              check_finite_every=1)),
        ("bitflip", True,
         dict(recovery=rec(["bitflip@2"]), consistency_every=1,
              max_inflight_steps=1)),
        ("grad_skew", True,
         dict(recovery=rec(["grad_skew@2"]), consistency_every=1,
              max_inflight_steps=1)),
        ("desync", True,
         dict(recovery=rec(["desync@2"]), consistency_every=1,
              max_inflight_steps=1)),
    ]


def build_tenants(workdir: str, rng: random.Random, n_tenants: int,
                  epochs: int) -> list:
    """The campaign's initial fleet: heterogeneous workloads cycling
    cnn / lm / pipeline / moe, each with a fault plan drawn from the
    menu. Placement rules baked in:

    * corruption kinds land only on the dp>=2 tenants (the trainers
      reject them anywhere else) — the dp4 cnn slice gives the quorum
      repair, a dp2 slice exercises the no-quorum restore instead;
    * ``tear_save`` only on cnn/pipeline (the LM trainer writes an extra
      per-epoch slot save, which would shift the torn-save occurrence
      off the good slot and break the deterministic pairing);
    * at least one tenant always draws a self-preempting kind
      (``preempt`` or ``stall``) — the campaign must exercise the
      preempt-checkpoint -> requeue -> exact-step resume loop even when
      the rng is unlucky, so the last plain-fault tenant is overridden
      when none drew one.
    """
    from distributed_model_parallel_tpu.config import MeshConfig
    from distributed_model_parallel_tpu.orchestrator import TenantSpec

    menu = _fault_menu(3, epochs)
    by_label = {m[0]: m for m in menu}
    plain = [m for m in menu if not m[1]]
    no_tear = [m for m in plain if m[0] != "tear_save"]
    specs, labels, overridable = [], [], []
    for i in range(n_tenants):
        workload = ("cnn", "lm", "pipeline", "moe")[i % 4]
        prio = rng.randint(0, 2)
        name = f"t{i}_{workload}"
        if workload == "cnn":
            # dp4: enough replicas for a majority-quorum repair, so the
            # corruption drills prefer this slice.
            label, _, kw = rng.choice(menu)
            cfg = _cnn_config(workdir, name, 4, epochs, **kw)
            spec = TenantSpec(name=name, workload="cnn", config=cfg,
                              priority=prio)
        elif workload == "pipeline":
            # Single-controller pipeline: no replicated state, no
            # corruption drills (the trainer rejects them loudly); the
            # pipeline-specific recovery paths (per-stage restore, LR
            # shrink rebuild) are exercised by nan/preempt/stall.
            label, _, kw = rng.choice(plain)
            cfg = _cnn_config(workdir, name, 1, epochs,
                              mesh=MeshConfig(data=1, stage=2),
                              num_microbatches=2, **kw)
            spec = TenantSpec(name=name, workload="pipeline", config=cfg,
                              priority=prio)
        else:                                    # lm / moe
            label, _, kw = rng.choice(no_tear)
            kw = dict(kw)
            kw.pop("max_inflight_steps", None)   # LM syncs every step
            cfg = _lm_config(workdir, name, 2, epochs,
                             moe=2 if workload == "moe" else 0, **kw)
            spec = TenantSpec(name=name, workload="lm", config=cfg,
                              priority=prio)
        specs.append(spec)
        labels.append(label)
        if workload in ("lm", "moe"):
            overridable.append(i)
    if not any(lb in ("preempt", "stall") for lb in labels) and overridable:
        i = overridable[-1]
        label, _, kw = by_label["preempt"]
        workload = ("cnn", "lm", "pipeline", "moe")[i % 4]
        cfg = _lm_config(workdir, specs[i].name, 2, epochs,
                         moe=2 if workload == "moe" else 0, **kw)
        specs[i] = TenantSpec(name=specs[i].name, workload="lm",
                              config=cfg, priority=specs[i].priority)
        labels[i] = label
    return specs


# ---------------------------------------------------------------------------
# one campaign
# ---------------------------------------------------------------------------

def run_campaign(args, workdir: str, seed: int) -> tuple[dict, bool]:
    from distributed_model_parallel_tpu.config import RecoveryConfig
    from distributed_model_parallel_tpu.orchestrator import (
        Orchestrator,
        TenantSpec,
    )
    from distributed_model_parallel_tpu.utils.telemetry import merge_streams
    from scripts.dmp_report import build_fleet_report, pair_faults

    rng = random.Random(seed)
    if args.tenants < 3:
        raise SystemExit("--tenants must be >= 3 (a soak below three "
                         "concurrent tenants is a chaos drill, not a soak "
                         "— scripts/dmp_chaos.py covers those)")
    orch = Orchestrator(workdir=os.path.join(workdir, "fleet"),
                        quantum=args.quantum)
    for spec in build_tenants(workdir, rng, args.tenants, args.epochs):
        orch.submit(spec)

    # Event schedule: rounds are the campaign's clock, so a fixed seed
    # fires the same event at the same fleet state every run. Events
    # land EARLY (the fast campaign is only a handful of rounds long) so
    # they hit a busy fleet, not a drained one.
    churn_round = None if args.no_churn else rng.randint(1, 2)
    shrink_round = None if args.no_shrink else \
        (churn_round or 1) + rng.randint(1, 2)
    grow_round = None if shrink_round is None \
        else shrink_round + rng.randint(2, 3)
    events: dict = {"churn": None, "shrink": None, "grow": None}

    def on_round(o: Orchestrator, r: int) -> None:
        if churn_round is not None and r == churn_round \
                and events["churn"] is None:
            # Tenant churn + priority preemption in one event: a
            # high-priority arrival on a full fleet must evict the
            # lowest-priority victim through the real preempt-checkpoint
            # path.
            cfg = _cnn_config(workdir, "hi_burst", 4, 1,
                              recovery=RecoveryConfig(max_retries=1))
            o.submit(TenantSpec(name="hi_burst", workload="cnn",
                                config=cfg, priority=9))
            events["churn"] = r
        if shrink_round is not None and r == shrink_round \
                and events["shrink"] is None:
            events["shrink"] = {"round": r, "revoked": list(o.shrink(2))}
        if grow_round is not None and r == grow_round \
                and events["grow"] is None:
            events["grow"] = {"round": r, "restored": list(o.grow())}

    t0 = time.monotonic()
    summary = orch.run(on_round=on_round, max_rounds=2000)
    orch.close(rounds=summary["rounds"])

    merged = merge_streams(orch.telemetry_paths())
    print(build_fleet_report(merged))
    ledger = pair_faults(merged)
    unpaired = [r for r in ledger if not r["paired"]]
    tenants = summary["tenants"]
    incomplete = [n for n, t in tenants.items() if t["state"] != "completed"]
    preempted = {n: t["preemptions"] for n, t in tenants.items()
                 if t["preemptions"]}
    fault_kinds = sorted({r["fault"] for r in ledger})
    out = {
        "soak": "multi-tenant-chaos-campaign",
        "mode": args.mode,
        "seed": seed,
        "rounds": summary["rounds"],
        "wall_s": round(time.monotonic() - t0, 1),
        "tenants": {n: t["state"] for n, t in tenants.items()},
        "heterogeneous_workloads": sorted({t["workload"]
                                           for t in tenants.values()}),
        "faults_injected": fault_kinds,
        "faults_paired": len(ledger) - len(unpaired),
        "faults_unpaired": [f"{r['tenant']}:{r['fault']}" for r in unpaired],
        "preemptions": preempted,
        "resumes_exact": summary["all_resumes_exact"],
        "unrecovered": summary["unrecovered"],
        "events": events,
        "telemetry": orch.telemetry_paths(),
    }
    ok = (not summary["unrecovered"]
          and not incomplete
          and summary["all_resumes_exact"]
          and not unpaired
          and bool(ledger)
          and (args.no_shrink or events["shrink"] is not None)
          and (args.no_churn or events["churn"] is not None))
    return out, ok


# ---------------------------------------------------------------------------
# the degradation scenario: straggler quarantine -> migration -> grow-back
# ---------------------------------------------------------------------------

def run_degradation_campaign(args, workdir: str, seed: int
                             ) -> tuple[dict, bool]:
    """The device-health drill (utils/health.py), end to end on the real
    stack: a ``slow_device`` degradation ramps up on the victim tenant's
    dp=4 slice until the health sentinel quarantines it; the orchestrator
    proactively migrates the victim through the ordinary
    preempt-checkpoint path onto the only free devices (dp=2 — migrated
    AND shrunk below its requested dp); after probation the quarantined
    devices are reinstated and the grow-back pass expands the victim
    back to dp=4 at the exact global step. A ``flaky_sync`` degradation
    rides on the bystander tenant with a sub-threshold magnitude — the
    negative control: intermittent jitter must NOT cost it its slice.

    Gates (non-zero exit when any fails):

    1. the victim's whole degraded slice is quarantined within 8 steps
       of the slow_device injection firing;
    2. the victim is migrated onto disjoint devices at dp=2 (shrunk);
    3. it is back at its requested dp=4 by campaign end (>= 1 grow-back)
       and EVERY resume landed at the exact global step (the bitwise
       resume accounting the orchestrator keeps);
    4. zero unrecovered tenants, everyone completes;
    5. the bystander's devices are never quarantined and it is never
       preempted (no false-positive quarantine from sub-threshold
       jitter).
    """
    from distributed_model_parallel_tpu.config import RecoveryConfig
    from distributed_model_parallel_tpu.orchestrator import (
        Orchestrator,
        TenantSpec,
    )
    from distributed_model_parallel_tpu.utils.health import (
        DeviceHealthMonitor,
        HealthPolicy,
    )
    from distributed_model_parallel_tpu.utils.telemetry import (
        merge_streams,
        read_records,
    )
    from scripts.dmp_report import build_fleet_report

    # Sized for the fast tier: ~3 outlier steps to quarantine, 3 quiet
    # ticks to reinstate; the absolute outlier floor (0.25s) keeps CI
    # host jitter from tripping the drill while the 0.4s-ramp injection
    # clears it on its first degraded step.
    monitor = DeviceHealthMonitor(HealthPolicy(
        warmup=3, outlier_factor=3.0, min_outlier_s=0.25,
        outlier_penalty=0.25, quarantine_below=0.35,
        reinstate_above=0.8, min_probation_ticks=3, idle_credit=0.25))
    orch = Orchestrator(workdir=os.path.join(workdir, "fleet"),
                        quantum=args.quantum, health=monitor)
    # The victim: requested dp=4, a slow_device ramp firing at step 6
    # (after the health baseline warms up), per-step drains so every
    # degraded step is an observation.
    victim_cfg = _cnn_config(
        workdir, "victim", 4, 6,
        recovery=RecoveryConfig(max_retries=1,
                                faults=("slow_device@6:0.4",)),
        max_inflight_steps=1)
    # The bystander: dp=2, long enough to hold its slice through the
    # victim's whole journey, with sub-threshold intermittent sync
    # stalls (0.03s << the 0.25s outlier floor).
    steady_cfg = _cnn_config(
        workdir, "steady", 2, 10,
        recovery=RecoveryConfig(max_retries=1,
                                faults=("flaky_sync@1:0.03",)),
        max_inflight_steps=1)
    victim = orch.submit(TenantSpec(name="victim", workload="cnn",
                                    config=victim_cfg))
    orch.submit(TenantSpec(name="steady", workload="cnn",
                           config=steady_cfg))

    t0 = time.monotonic()
    summary = orch.run(max_rounds=2000)
    orch.close(rounds=summary["rounds"])

    merged = merge_streams(orch.telemetry_paths())
    print(build_fleet_report(merged))

    fleet = read_records(os.path.join(workdir, "fleet", "fleet.jsonl"))
    quarantined = sorted({d for r in fleet if r.get("kind") == "health"
                          and r.get("event") == "quarantine"
                          for d in r.get("devices", [])})
    reinstated = sorted({d for r in fleet if r.get("kind") == "health"
                         and r.get("event") == "reinstate"
                         for d in r.get("devices", [])})
    vt = summary["tenants"]["victim"]
    st = summary["tenants"]["steady"]
    grants = {t: [a["devices"] for a in summary["assignments"]
                  if a["tenant"] == t] for t in ("victim", "steady")}
    fire_step = next((r.get("index") for r in merged
                      if r.get("kind") == "fault"
                      and r.get("fault") == "slow_device"), None)
    migrate_step = next((r.get("global_step") for r in fleet
                         if r.get("kind") == "tenant"
                         and r.get("event") == "preempt-requested"
                         and str(r.get("reason", ""))
                         .startswith("device-degraded")), None)
    incomplete = [n for n, t in summary["tenants"].items()
                  if t["state"] != "completed"]
    first_slice = set(grants["victim"][0]) if grants["victim"] else set()
    migrated = [g for g in grants["victim"][1:] if not set(g) & first_slice]
    out = {
        "soak": "degradation-campaign",
        "scenario": "degradation",
        "seed": seed,
        "rounds": summary["rounds"],
        "wall_s": round(time.monotonic() - t0, 1),
        "tenants": {n: t["state"] for n, t in summary["tenants"].items()},
        "quarantined_devices": quarantined,
        "reinstated_devices": reinstated,
        "slow_device_fired_at_step": fire_step,
        "migrated_at_step": migrate_step,
        "victim_grants": grants["victim"],
        "victim_grow_backs": vt["grow_backs"],
        "victim_requested": vt["requested_devices"],
        "victim_granted_sizes": vt["granted_sizes"],
        "steady_preemptions": st["preemptions"],
        "resumes_exact": summary["all_resumes_exact"],
        "unrecovered": summary["unrecovered"],
        "telemetry": orch.telemetry_paths(),
    }
    steady_slice = set(grants["steady"][0]) if grants["steady"] else set()
    ok = (not summary["unrecovered"]
          and not incomplete
          # gate 1: the degraded slice quarantined, promptly
          and set(quarantined) == first_slice and bool(first_slice)
          and fire_step is not None and migrate_step is not None
          and 0 <= migrate_step - fire_step <= 8
          # gate 2: migrated onto disjoint devices, shrunk below request
          and bool(migrated) and len(migrated[0]) == 2
          # gate 3: grown back to the requested dp at the exact step
          and vt["grow_backs"] >= 1
          and vt["granted_sizes"][-1] == vt["requested_devices"] == 4
          and summary["all_resumes_exact"]
          # gate 4: probation healed the quarantined devices
          and set(reinstated) == set(quarantined)
          # gate 5: the flaky-but-healthy bystander kept its slice
          and not (set(quarantined) & steady_slice)
          and st["preemptions"] == 0)
    _ = victim
    return out, ok


# ---------------------------------------------------------------------------
# the overload scenario: 2x offered load, shed typed, degrade gracefully
# ---------------------------------------------------------------------------

def run_overload_campaign(args, workdir: str, seed: int
                          ) -> tuple[dict, bool]:
    """The serving-fleet overload drill (docs/SERVING.md "Overload and
    graceful degradation"), end to end on the real stack:

    Phase A measures clean capacity — the same request population,
    closed loop, no deadlines, nothing sheds — and records every
    request's reference tokens. Phase B replays the population as an
    open-loop trace at **2x capacity** (plus a 0.3x cool-down tail so
    the brownout has live traffic to resolve against), with the whole
    overload plane armed: queue-wait budgets + total deadlines, bounded
    fleet/engine queues, the brownout ladder, and an injected
    ``admission_fail`` burst on one replica to exercise the router's
    circuit breaker.

    Gates (non-zero exit when any fails):

    1. goodput — tokens/s of requests completed within deadline, over
       the saturated window — >= ``--goodput-band`` of clean capacity;
    2. every non-completed request is accounted for by a typed ``shed``
       record (queue-deadline / total-deadline / queue-full) — zero
       silent drops, zero real failures;
    3. the fleet queue and every engine queue stay bounded throughout
       (asserted every round, not just at the end);
    4. brownout fires under load (typed ``brownout`` records) and
       resolves back to level 0 after it;
    5. the circuit breaker opens on the injected admission failures and
       closes again through a half-open probe;
    6. every completed request's tokens are bitwise identical to its
       clean-run reference (level-3-clamped requests: the bitwise
       prefix) — degradation moves *which* requests complete and
       *when*, never their tokens.
    """
    import jax
    import numpy as np

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.serve import (
        Engine,
        ServeConfig,
        ServeFleet,
    )
    from distributed_model_parallel_tpu.serve.scheduler import RequestState
    from distributed_model_parallel_tpu.utils.telemetry import (
        TelemetryRun,
        join_request_traces,
        read_records,
    )
    from scripts.dmp_report import build_report

    rng = np.random.default_rng(seed)
    n_replicas = 2
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=128,
                                pos_embedding="rope")
    params = tfm.init_params(jax.random.key(0), cfg)
    n_slots, page, chunk, max_len = 2, 8, 4, 64
    base = dict(n_slots=n_slots, page_size=page,
                n_pages=(n_slots + 1) * (-(-max_len // page)),
                max_seq_len=max_len, prefill_chunk=chunk)
    n_over, n_cool = 28, 8
    population = [dict(
        rid=f"o{i}",
        prompt=[int(x) for x in rng.integers(0, 64,
                                             int(rng.integers(4, 13)))],
        gen=int(rng.integers(8, 25)),
        priority="batch" if i % 3 == 2 else "interactive")
        for i in range(n_over + n_cool)]

    os.makedirs(workdir, exist_ok=True)
    stream = os.path.join(workdir, "overload.jsonl")
    tel = TelemetryRun(stream, run="overload-drill")
    t0 = time.monotonic()
    Engine(params, cfg, ServeConfig(**base), slo_metrics=False).warmup()

    # -- phase A: clean capacity + per-request reference tokens
    cap_fleet = ServeFleet(params, cfg, ServeConfig(**base), n_replicas,
                           telemetry=tel)
    for r in population:
        cap_fleet.submit(r["prompt"], r["gen"], rid=r["rid"])
    cap = cap_fleet.run()
    cap_fleet.close()
    if cap["requests_failed"] or cap["requests_shed"]:
        raise RuntimeError("clean capacity run shed or failed requests")
    reference = {q.rid: list(q.generated) for q in cap_fleet.results()}
    capacity = cap["tokens_per_s"] or 0.0
    wall_a = max(cap["wall_s"], 1e-3)

    # -- phase B: the same population at 2x offered load + cool-down
    mean_tokens = sum(len(v) for v in reference.values()) / len(reference)
    t, arrivals = 0.0, []
    for i in range(len(population)):
        rate = ((2.0 if i < n_over else 0.3) * capacity / mean_tokens
                if capacity else 1.0)
        t += float(rng.exponential(1.0 / rate))
        arrivals.append(t)
    serve = ServeConfig(
        **base,
        # Budgets scale with the measured capacity run so the drill is
        # machine-speed-independent; the absolute floors keep a very
        # fast host from shedding on scheduler-granularity jitter.
        queue_budget_s=max(0.15 * wall_a, 0.08),
        deadline_s=max(1.2 * wall_a, 0.6),
        max_queue=2 * n_slots,
        brownout=True,
        brownout_ttft_target_s=max(0.08 * wall_a, 0.05),
        brownout_budget=0.25,
        brownout_window_s=max(0.10 * wall_a, 0.1),
        brownout_max_new=8,
        brownout_hold_iters=4)
    fleet = ServeFleet(params, cfg, serve, n_replicas, telemetry=tel,
                       faults=("admission_fail@0:6",), fault_replica="r1")
    queue_bounded = True

    def hook(rnd):
        nonlocal queue_bounded
        if len(fleet._pending) > fleet._max_pending + len(population):
            queue_bounded = False     # never: trim runs every round
        arrived = sum(1 for r in fleet._pending if r.arrival_s <= fleet._now)
        if arrived > fleet._max_pending + n_replicas:
            queue_bounded = False     # slack: one round's arrivals
        for rep in fleet.replicas:
            if len(rep.engine.sched.queue) > serve.max_queue:
                queue_bounded = False

    fleet.step_hook = hook
    for r, arr in zip(population, arrivals):
        fleet.submit(r["prompt"], r["gen"], rid=r["rid"], arrival_s=arr,
                     priority=r["priority"])
    over = fleet.run()
    tel.finish()
    print(build_report(read_records(stream)))

    results = {q.rid: q for q in fleet.results()}
    eng0 = fleet.replicas[0].engine
    # Goodput over the SATURATED window: up to the last phase-1
    # request's completion (the cool-down tail intentionally
    # under-offers, so whole-run tokens/s would understate the fleet).
    phase1 = [results[r["rid"]] for r in population[:n_over]]
    t_end = max((q.t_done for q in phase1 if q.t_done is not None),
                default=None)
    goodput = (sum(len(q.generated) for q in results.values()
                   if q.state is RequestState.COMPLETED
                   and eng0._in_deadline(q) and q.t_done is not None
                   and q.t_done <= t_end) / t_end
               if t_end else 0.0)
    # Bitwise parity: completed tokens == the clean-run reference
    # (level-3-clamped requests: its prefix).
    mismatched = []
    for q in results.values():
        if q.state is not RequestState.COMPLETED:
            continue
        ref = reference[q.rid]
        ok = (q.generated == ref[:len(q.generated)]
              if q.max_new_requested is not None else q.generated == ref)
        if not ok:
            mismatched.append(q.rid)
    # Typed accounting: every non-completed request sheds on the record.
    recs = read_records(stream)
    shed_recorded = {r.get("request") for r in recs
                     if r.get("kind") == "shed"}
    unaccounted = [q.rid for q in results.values()
                   if q.state is not RequestState.COMPLETED
                   and (q.shed_reason is None
                        or q.rid not in shed_recorded)]
    # Trace-plane accounting (gate 7): every request in BOTH phases —
    # including the shed/expired ones — must reconstruct a complete
    # causally ordered rtrace timeline with exactly one terminal event.
    traces = join_request_traces(recs)
    trace_orphans = sorted(t["trace"] for t in traces.values()
                           if t["orphan"])
    bo_recs = [r for r in recs if r.get("kind") == "brownout"]
    bo_fired = any(r.get("level", 0) >= 1 for r in bo_recs)
    bo_final = [rep.engine.brownout.level for rep in fleet.replicas]
    brk = [r for r in recs if r.get("kind") == "breaker"]
    breaker_cycled = (any(r.get("state") == "open"
                          and r.get("replica") == "r1" for r in brk)
                      and fleet.breaker.snapshot().get("r1") == "closed")
    fleet.close()

    completed = [q for q in results.values()
                 if q.state is RequestState.COMPLETED]
    out = {
        "soak": "overload-campaign",
        "scenario": "overload",
        "seed": seed,
        "wall_s": round(time.monotonic() - t0, 1),
        "capacity_tokens_per_s": round(capacity, 1),
        "goodput_tokens_per_s": round(goodput, 1),
        "goodput_fraction": (round(goodput / capacity, 3)
                             if capacity else None),
        "goodput_band": args.goodput_band,
        "requests": len(population),
        "completed": len(completed),
        "shed_by_reason": over["shed_by_reason"],
        "requests_rejected": over["requests_rejected"],
        "requests_failed": over["requests_failed"],
        "unaccounted": unaccounted,
        "queue_bounded": queue_bounded,
        "brownout_fired": bo_fired,
        "brownout_final_levels": bo_final,
        "brownout_transitions": len(bo_recs),
        "breaker_cycled": breaker_cycled,
        "token_mismatches": mismatched,
        "clamped": sorted(q.rid for q in completed
                          if q.max_new_requested is not None),
        "rtrace_timelines": len(traces),
        "rtrace_orphans": trace_orphans,
        "telemetry": [stream],
    }
    ok = (goodput >= args.goodput_band * capacity
          and not unaccounted
          and over["requests_failed"] == 0
          and queue_bounded
          and bo_fired and all(lv == 0 for lv in bo_final)
          and breaker_cycled
          and not mismatched
          # The drill must actually EXERCISE the shed path (a drill
          # where nothing sheds proves nothing about typed accounting)
          # while still completing a real fraction of the offered work.
          and sum(over["shed_by_reason"].values()) >= 1
          and len(completed) >= len(population) // 3
          # gate 7: complete rtrace timelines, no orphan spans
          and bool(traces) and not trace_orphans)
    return out, ok


# ---------------------------------------------------------------------------
# the xray scenario: complete request timelines through a replica kill
# ---------------------------------------------------------------------------

def run_xray_campaign(args, workdir: str, seed: int) -> tuple[dict, bool]:
    """The request-tracing drill (docs/OBSERVABILITY.md "Request
    tracing"): seeded open-loop traffic on a two-replica fleet, one
    replica killed mid-stream and revived, and the whole run's
    ``rtrace`` plane audited for reconstruction fidelity.

    Gates (non-zero exit when any fails):

    1. the kill catches live requests and every request still completes
       (zero failures — the self-healing contract this drill rides on);
    2. EVERY submitted request reconstructs a complete causally ordered
       timeline: contiguous per-request seq, exactly one typed terminal
       event, zero orphan spans;
    3. the migration hops are linked — every drained request's
       ``export`` pairs with its destination ``import`` across the
       source/destination origins, and at least one hop exists;
    4. per-phase attribution (queue / prefill / decode /
       migration-pause / ...) sums to within 5% of each timeline's
       measured wall time.

    The joined timelines are written to ``xray_timelines.json`` in the
    campaign workdir — the artifact CI uploads on failure.
    """
    import jax
    import numpy as np

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.serve import ServeConfig, ServeFleet
    from distributed_model_parallel_tpu.utils.telemetry import (
        TelemetryRun,
        join_request_traces,
        read_records,
    )
    from scripts.dmp_xray import phase_gate_error, summarize

    rng = np.random.default_rng(seed)
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=128,
                                pos_embedding="rope")
    params = tfm.init_params(jax.random.key(0), cfg)
    n_slots, page, max_len = 2, 8, 64
    base = dict(n_slots=n_slots, page_size=page,
                n_pages=(n_slots + 1) * (-(-max_len // page)),
                max_seq_len=max_len, prefill_chunk=4)
    population = [dict(
        rid=f"x{i}",
        prompt=[int(x) for x in rng.integers(0, 64,
                                             int(rng.integers(4, 13)))],
        gen=int(rng.integers(10, 25)))
        for i in range(10)]

    os.makedirs(workdir, exist_ok=True)
    stream = os.path.join(workdir, "xray.jsonl")
    tel = TelemetryRun(stream, run="xray-drill")
    t0 = time.monotonic()
    fleet = ServeFleet(params, cfg, ServeConfig(**base), 2, telemetry=tel,
                       router_seed=seed, revive_after=3)
    kill = {"n": None}

    def hook(rnd):
        # Round 4: past warmup/prefill ramp, before the backlog drains —
        # the kill lands on a busy replica so drained requests carry
        # real committed KV (the export/import hop the drill audits).
        if rnd == 4 and kill["n"] is None:
            kill["n"] = fleet.kill_replica("r0")

    fleet.step_hook = hook
    for i, r in enumerate(population):
        fleet.submit(r["prompt"], r["gen"], rid=r["rid"], seed=i)
    summary = fleet.run()
    tel.finish()
    fleet.close()

    traces = join_request_traces(read_records(stream))
    orphans = sorted(t["trace"] for t in traces.values() if t["orphan"])
    hops = sum(len(t["hops"]) for t in traces.values())
    phase_bad = sorted(t["trace"] for t in traces.values()
                       if phase_gate_error(t) > 0.05)
    artifact = os.path.join(workdir, "xray_timelines.json")
    with open(artifact, "w") as f:
        json.dump({"summary": summarize(traces),
                   "traces": list(traces.values())}, f, default=str)

    out = {
        "soak": "xray-campaign",
        "scenario": "xray",
        "seed": seed,
        "wall_s": round(time.monotonic() - t0, 1),
        "requests": len(population),
        "completed": summary["requests_completed"],
        "failed": summary["requests_failed"],
        "migrated_at_kill": kill["n"],
        "migrations": summary["migrations"],
        "rtrace_timelines": len(traces),
        "rtrace_orphans": orphans,
        "migration_hops": hops,
        "phase_sum_mismatches": phase_bad,
        "artifact": artifact,
        "telemetry": [stream],
    }
    ok = (summary["requests_failed"] == 0
          and summary["requests_completed"] == len(population)
          and (kill["n"] or 0) > 0
          # gate 2: one complete timeline per request, zero orphans
          and len(traces) == len(population)
          and not orphans
          # gate 3: the kill's migrations show up as linked hops
          and hops >= 1
          # gate 4: phase attribution accounts for the wall time
          and not phase_bad)
    return out, ok


# ---------------------------------------------------------------------------
# the fleet scenarios: production traffic + cell-scale correlated failures
# ---------------------------------------------------------------------------

FLEET_SCENARIOS = ("failover", "flashcrowd", "flood", "diurnal")


class _FakeDev:
    """Pool bookkeeping device: replicas run replicated on CPU; the ids
    are the quarantine/grow-back accounting the drill gates on (the same
    stand-in tests/test_fleet.py uses for DevicePool)."""

    def __init__(self, i: int):
        self.id = i


def _schedule_digest(records: list[dict]) -> dict:
    """Normalized fleet event schedule + its hash: router assignments,
    migration hops, typed sheds, breaker transitions and cell events in
    stream order, with timestamps and load snapshots stripped — the
    replay-determinism contract is about WHAT happened to WHOM in WHICH
    round, not microsecond jitter (tests/test_soak.py replays a scenario
    twice and compares digests)."""
    import hashlib

    keys = []
    for r in records:
        k = r.get("kind")
        if k == "router":
            keys.append(["router", r.get("request"), r.get("replica"),
                         r.get("reason"), r.get("round")])
        elif k == "migration":
            keys.append(["migration", r.get("request"),
                         r.get("from_replica"), r.get("to_replica"),
                         r.get("round")])
        elif k == "shed":
            keys.append(["shed", r.get("request"), r.get("reason"),
                         r.get("state")])
        elif k == "breaker":
            keys.append(["breaker", r.get("replica"), r.get("state"),
                         r.get("round")])
        elif k == "cell":
            keys.append(["cell", r.get("event"), r.get("cell"),
                         r.get("round")])
    blob = json.dumps(keys, separators=(",", ":")).encode()
    return {"events": len(keys),
            "sha256": hashlib.sha256(blob).hexdigest()}


def run_fleet_scenario(args, workdir: str, seed: int,
                       scenario: str) -> tuple[dict, bool]:
    """One production-traffic + correlated-failure drill on a celled
    serving fleet (docs/SERVING.md "Scenario catalog").

    Three deterministic runs on a virtual clock (serve/traffic.SimClock
    — no wall-clock sleeps, so the event schedule is a pure function of
    the seed):

    * **reference** — every request of the trace on one clean engine,
      closed loop, no deadlines: the bitwise per-request token
      references;
    * **clean** — the scenario's traffic through the SAME fleet shape
      with no fault armed: the goodput baseline (for ``flood`` the
      clean trace is the background WITHOUT the flood burst — the gate
      is that the flood must not starve the background class);
    * **chaos** — the same traffic with the scenario's correlated fault
      riding the cell site (utils/faults.py).

    Scenario -> traffic x fault:

    ==============  ==========================  =========================
    scenario        traffic (serve/traffic.py)  correlated fault
    ==============  ==========================  =========================
    ``failover``    mixed tenants (per-tenant   ``kill_cell`` mid-trace +
                    SLO classes)                exact-slice grow-back
    ``flashcrowd``  diurnal base + rectangular  ``slow_cell`` through the
                    arrival spike               spike (brownout armed)
    ``flood``       interactive background +    none — the flood IS the
                    long-prompt batch flood     event (overload plane)
    ``diurnal``     one compressed diurnal      ``partition`` across the
                    cycle                       peak, heal + drain-out
    ==============  ==========================  =========================

    Gates (non-zero exit when any fails):

    1. zero lost requests — every submitted request either completes or
       lands on a typed shed record; zero real failures;
    2. bitwise token parity — every completed request's tokens match
       its reference (brownout-clamped requests: the bitwise prefix);
    3. complete rtrace timelines — one joined timeline per submitted
       request, zero orphan spans;
    4. goodput — in-deadline completed tokens per virtual second >=
       ``--goodput-band`` of the clean run's rate (``flood``: over the
       background population on both sides);
    5. the scenario's event provably happened (cell kill + grow-back
       records, slow_cell fired, flood burst present, partition + heal
       records) — a drill whose fault never fired proves nothing;
    6. ``failover`` only: EXACT grow-back — every replica live again on
       exactly its original device slice;
    7. billing (serve/capacity.py): the chaos stream passes every
       capacity-gate invariant (duty partition, chip bound, 1:1
       terminal meter/rtrace pairing), metering serve-loop overhead
       measures < 2% of iteration wall, and a metering-off rerun of the
       clean trace yields a byte-identical schedule digest.

    The normalized event schedule (``_schedule_digest``) rides the
    summary: same seed => same digest, the replay-determinism property
    tests/test_soak.py pins.
    """
    import jax

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.orchestrator.scheduler import (
        DevicePool,
    )
    from distributed_model_parallel_tpu.serve import (
        Engine,
        ServeConfig,
        ServeFleet,
        SimClock,
        adversarial_flood,
        diurnal,
        flash_crowd,
        mixed_tenants,
    )
    from distributed_model_parallel_tpu.serve.scheduler import RequestState
    from distributed_model_parallel_tpu.utils.telemetry import (
        TelemetryRun,
        join_request_traces,
        read_records,
    )
    from scripts.dmp_report import build_report

    n_replicas, n_cells = args.replicas, args.cells
    if n_cells < 2:
        raise SystemExit("fleet scenarios need --cells >= 2 (failover "
                         "needs a surviving cell to fail over to)")
    if n_replicas < n_cells:
        raise SystemExit(f"--replicas {n_replicas} < --cells {n_cells}: "
                         f"every cell needs at least one replica")

    dt = 0.02
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=128,
                                pos_embedding="rope")
    params = tfm.init_params(jax.random.key(0), cfg)
    n_slots, page, max_len = 2, 8, 64
    base = dict(n_slots=n_slots, page_size=page,
                n_pages=(n_slots + 1) * (-(-max_len // page)),
                max_seq_len=max_len, prefill_chunk=4)

    # Scenario -> (chaos trace, clean trace, fault plan, serve config,
    # revive_after). Rates are requests per VIRTUAL second; one fleet
    # round advances dt, so fault `at` indexes (cell-site polls == fleet
    # rounds) map to virtual time as at * dt.
    overload_kw = dict(queue_budget_s=1.2, deadline_s=3.0,
                       max_queue=2 * n_slots, brownout=True,
                       brownout_ttft_target_s=0.3, brownout_budget=0.25,
                       brownout_window_s=0.2, brownout_max_new=8,
                       brownout_hold_iters=4)
    revive_after = None
    if scenario == "failover":
        trace = mixed_tenants(seed, horizon_s=3.0, tenants={
            # ~44 req/s against 16x2 slots: enough standing load that
            # the kill provably catches residents mid-decode (the
            # migration path is the thing under drill).
            "web": {"rate": 22.0, "priority": "interactive"},
            "mobile": {"rate": 12.0, "priority": "interactive"},
            "etl": {"rate": 10.0, "priority": "batch",
                    "gen": (14, 22)},
        })
        clean_trace = trace
        faults = ("kill_cell@50",)      # ~1.0 virtual s: mid-trace, busy
        serve = ServeConfig(**base)     # no deadlines: everything lands
        revive_after = 45
    elif scenario == "flashcrowd":
        # Spike sized PAST the fleet's decode capacity (~150 req/s at
        # 16x2 slots) so the brownout/shed machinery actually engages.
        trace = flash_crowd(seed, horizon_s=3.0, base_rate=8.0,
                            spike_at_s=1.0, spike_s=0.5, spike_rate=160.0)
        clean_trace = trace
        faults = ("slow_cell@45:2",)    # the cell slows INTO the spike
        serve = ServeConfig(**base, **overload_kw)
    elif scenario == "flood":
        # 48 outsized batch requests landing at once: more than the
        # fleet's 16x2 slots and most of its bounded queue — the
        # priority shed order must keep the interactive background
        # whole while the flood tenant eats the typed sheds.
        kw = dict(horizon_s=3.0, base_rate=8.0, flood_at_s=1.0)
        trace = adversarial_flood(seed, flood_n=48, **kw)
        # Same seed, no burst: the background stream is drawn FIRST from
        # the rng, so it is bit-identical with and without the flood.
        clean_trace = adversarial_flood(seed, flood_n=0, **kw)
        faults = ()                     # the traffic IS the event
        # Tighter queue budget than the other overload scenarios: the
        # flood's second wave must provably hit the typed shed path,
        # not merely queue politely behind the first.
        serve = ServeConfig(**base, **{**overload_kw,
                                       "queue_budget_s": 0.5,
                                       "deadline_s": 2.5})
    elif scenario == "diurnal":
        trace = diurnal(seed, horizon_s=4.0, base_rate=4.0,
                        peak_rate=18.0)
        clean_trace = trace
        faults = ("partition@90:30",)   # unreachable across the peak
        serve = ServeConfig(**base, queue_budget_s=1.5, deadline_s=3.5,
                            max_queue=2 * n_slots)
    else:
        raise SystemExit(f"unknown fleet scenario {scenario!r}")

    os.makedirs(workdir, exist_ok=True)
    t0 = time.monotonic()

    # -- reference: bitwise per-request tokens, one clean engine
    ref_eng = Engine(params, cfg, ServeConfig(**base), slo_metrics=False)
    ref_eng.warmup()
    ref_reqs = [ref_eng.submit(r["prompt"], r["max_new"], rid=r["rid"],
                               seed=r["seed"]) for r in trace]
    ref_eng.run()
    bad_ref = [q.rid for q in ref_reqs
               if q.state is not RequestState.COMPLETED]
    if bad_ref:
        raise RuntimeError(f"reference run failed requests: {bad_ref}")
    reference = {q.rid: list(q.generated) for q in ref_reqs}

    def run_fleet(trace_, faults_, stream, label, meter=True):
        tel = TelemetryRun(stream, run=label)
        fleet = ServeFleet(
            params, cfg, serve, n_replicas,
            pool=DevicePool([_FakeDev(i) for i in range(n_replicas)]),
            telemetry=tel, cells=n_cells, router_seed=seed,
            clock=SimClock(dt), faults=faults_,
            revive_after=revive_after, meter=meter)
        slices = {r.name: r.device_ids for r in fleet.replicas}
        for r in trace_:
            fleet.submit(r["prompt"], r["max_new"], rid=r["rid"],
                         arrival_s=r["arrival_s"], seed=r["seed"],
                         priority=r["priority"], tenant=r.get("tenant"))
        s = fleet.run(max_rounds=20000)
        tel.finish()
        fleet.close()
        return fleet, s, slices

    def goodput_rate(fleet, s, rids=None):
        eng0 = fleet.replicas[0].engine
        toks = sum(len(q.generated) for q in fleet.results()
                   if q.state is RequestState.COMPLETED
                   and (rids is None or q.rid in rids)
                   and eng0._in_deadline(q))
        return toks / max(s["wall_s"], 1e-9)

    # -- clean: the goodput baseline for the same fleet shape
    clean_stream = os.path.join(workdir, f"{scenario}_clean.jsonl")
    clean_fleet, clean_sum, _ = run_fleet(clean_trace, (), clean_stream,
                                          f"{scenario}-clean")
    band_rids = ({r["rid"] for r in clean_trace}
                 if scenario == "flood" else None)
    clean_rate = goodput_rate(clean_fleet, clean_sum, band_rids)

    # -- metering-off A/B (same methodology as the crashrecovery
    # journal gate): the clean trace rerun with the billing plane OFF
    # must produce a byte-identical normalized event schedule — the
    # meter observes the serve loop, it must never steer it.
    meteroff_stream = os.path.join(workdir, f"{scenario}_meteroff.jsonl")
    run_fleet(clean_trace, (), meteroff_stream,
              f"{scenario}-meteroff", meter=False)
    clean_digest = _schedule_digest(read_records(clean_stream))
    meteroff_digest = _schedule_digest(read_records(meteroff_stream))
    metering_transparent = (clean_digest["sha256"]
                            == meteroff_digest["sha256"])

    # -- chaos: the same traffic with the correlated fault armed
    stream = os.path.join(workdir, f"{scenario}.jsonl")
    fleet, chaos, slices = run_fleet(trace, faults, stream,
                                     f"{scenario}-chaos")
    chaos_rate = goodput_rate(fleet, chaos, band_rids)
    recs = read_records(stream)
    print(build_report(recs))

    # -- capacity gate (serve/capacity.py): the billing invariants over
    # the chaos stream — duty buckets partition each replica's wall,
    # billed chip-seconds fit inside the iterated wall, every terminal
    # rtrace pairs 1:1 with a terminal meter record — plus the metering
    # serve-loop overhead the acceptance pins at < 2%.
    from distributed_model_parallel_tpu.serve.capacity import (
        build_capacity,
        check_invariants,
    )

    cap = build_capacity(recs)
    billing_failures = check_invariants(recs)
    if not any(r.get("kind") == "meter" for r in recs):
        billing_failures.append("no meter records in chaos stream")
    metering_overhead = cap["metering_overhead"]["fraction"]

    results = {q.rid: q for q in fleet.results()}
    # Gate 2: bitwise parity (brownout-clamped: the bitwise prefix).
    mismatched = []
    for q in results.values():
        if q.state is not RequestState.COMPLETED:
            continue
        ref = reference[q.rid]
        ok_tokens = (q.generated == ref[:len(q.generated)]
                     if q.max_new_requested is not None
                     else q.generated == ref)
        if not ok_tokens:
            mismatched.append(q.rid)
    # Gate 1: zero lost — typed shed record for every non-completion.
    shed_recorded = {r.get("request") for r in recs
                     if r.get("kind") == "shed"}
    unaccounted = [q.rid for q in results.values()
                   if q.state is not RequestState.COMPLETED
                   and (q.shed_reason is None
                        or q.rid not in shed_recorded)]
    # Gate 3: one complete rtrace timeline per request, zero orphans.
    traces = join_request_traces(recs)
    trace_orphans = sorted(t["trace"] for t in traces.values()
                           if t["orphan"])
    # Gate 5: the scenario's event provably happened.
    cell_recs = [r for r in recs if r.get("kind") == "cell"]
    cell_events = sorted({r.get("event") for r in cell_recs})
    if scenario == "failover":
        event_seen = ("kill" in cell_events
                      and "grow-back" in cell_events
                      and chaos["migrations"] >= 1)
    elif scenario == "flashcrowd":
        event_seen = any(s_.kind == "slow_cell"
                         for s_ in fleet.injector.fired)
    elif scenario == "flood":
        flood_rids = {r["rid"] for r in trace} - {r["rid"]
                                                  for r in clean_trace}
        event_seen = (bool(flood_rids)
                      and chaos["requests_shed"] >= 1
                      and all(
                          results[rid].state is RequestState.COMPLETED
                          or results[rid].shed_reason is not None
                          for rid in flood_rids))
    else:                                              # diurnal
        event_seen = ("partition" in cell_events
                      and "heal" in cell_events)
    # Gate 6 (failover): exact-slice grow-back — every replica live on
    # its original devices, re-held in the pool under its own tenant.
    grow_back_exact = all(
        r.state == "live"
        and fleet.pool.assigned_ids(f"serve-{r.name}") == slices[r.name]
        for r in fleet.replicas) if scenario == "failover" else None

    artifact = os.path.join(workdir, f"{scenario}_timelines.json")
    with open(artifact, "w") as f:
        json.dump({"scenario": scenario, "seed": seed,
                   "traces": list(traces.values())}, f, default=str)

    goodput_fraction = (chaos_rate / clean_rate if clean_rate else None)
    out = {
        "soak": "fleet-scenario-campaign",
        "scenario": scenario,
        "seed": seed,
        "wall_s": round(time.monotonic() - t0, 1),
        "replicas": n_replicas,
        "cells": chaos["cells"]["layout"] if chaos.get("cells") else None,
        "requests": len(trace),
        "completed": chaos["requests_completed"],
        "failed": chaos["requests_failed"],
        "shed_by_reason": chaos["shed_by_reason"],
        "unaccounted": unaccounted,
        "token_mismatches": mismatched,
        "clamped": sorted(q.rid for q in results.values()
                          if q.state is RequestState.COMPLETED
                          and q.max_new_requested is not None),
        "migrations": chaos["migrations"],
        "cell_kills": (chaos["cells"] or {}).get("cell_kills"),
        "cell_events": cell_events,
        "router_failovers": chaos["router"]["failovers"],
        "event_seen": event_seen,
        "grow_back_exact": grow_back_exact,
        "clean_goodput_tokens_per_vs": round(clean_rate, 1),
        "chaos_goodput_tokens_per_vs": round(chaos_rate, 1),
        "goodput_fraction": (round(goodput_fraction, 3)
                            if goodput_fraction is not None else None),
        "goodput_band": args.goodput_band,
        "rtrace_timelines": len(traces),
        "rtrace_orphans": trace_orphans,
        "schedule_digest": _schedule_digest(recs),
        "capacity": {k: cap[k] for k in (
            "tokens_per_s", "sustainable_tokens_per_s",
            "headroom_tokens_per_s", "headroom_fraction",
            "billed_chip_s", "billed_page_s", "meter_records",
            "tenants")},
        "billing_invariant_failures": billing_failures,
        "metering_overhead_fraction": round(metering_overhead, 5),
        "metering_transparent": metering_transparent,
        "artifact": artifact,
        "telemetry": [stream, clean_stream, meteroff_stream],
    }
    ok = (not unaccounted
          and chaos["requests_failed"] == 0
          and not mismatched
          and len(traces) == len(trace)
          and not trace_orphans
          and event_seen
          and (grow_back_exact is None or grow_back_exact)
          and goodput_fraction is not None
          and goodput_fraction >= args.goodput_band
          and not billing_failures
          and metering_overhead < 0.02
          and metering_transparent)
    return out, ok


def run_crashrecovery_scenario(args, workdir: str,
                               seed: int) -> tuple[dict, bool]:
    """Crash-consistency drill: the write-ahead request journal
    (serve/journal.py) under both hard-crash paths, on a virtual clock
    (docs/SERVING.md "Crash recovery").

    Six deterministic runs, one traffic trace (mixed tenants, no
    deadlines — every accepted request is owed a completion):

    * **reference** — the whole trace on one clean engine: bitwise
      per-request token references;
    * **journal-off clean** — the fleet with no journal: the schedule
      digest the journal must not perturb;
    * **journal-on clean** — same fleet + journal, no fault: gates the
      digest BYTE-IDENTICAL to journal-off (zero behavior change) and
      the journal's SERVE-LOOP write time (watermarks + terminals; the
      fsync'd intent is admission-path latency charged to submit(),
      reported separately) < 3% of summed engine iteration wall time;
    * **crash drill** (x2, same seed) — ``crash_replica`` fired
      mid-trace on the victim replica: engine, page pool and prefix
      tree discarded with no drain; every journaled non-terminal
      request must be re-admitted on a peer and finish bitwise against
      the reference, with a complete joined rtrace per request (the
      crash hop linked via the ``recovered`` event, zero orphans) —
      and the second run's schedule digest must equal the first's
      (replay-deterministic recovery);
    * **restart drill** — the fleet is ABANDONED mid-trace (no drain,
      no close-time flush: buffered watermarks die like a process), a
      torn partial line is appended to the journal (a crash mid-write
      at the fsync boundary), and ``ServeFleet.recover`` resumes from
      the journal alone on a second telemetry stream: the torn tail
      must be skipped (counted on ``telemetry_torn_lines``), every
      accepted request must complete bitwise exactly once (journal
      fold: zero pending, one terminal per intent), and the two
      streams must join into one complete timeline per request across
      the restart epoch.

    Gates (non-zero exit when any fails): zero accepted-and-lost and
    zero failures in every run; bitwise parity everywhere; journal-off
    digest == journal-on digest; journal overhead < 3%; crash + restart
    drills each provably fired (crash count, in-flight count at
    abandonment, torn-line count); exactly one terminal per trace;
    replay-deterministic crash digest; zero rtrace orphans with >= 1
    linked ``recovered`` hop per drill.
    """
    import jax

    from distributed_model_parallel_tpu.models import transformer as tfm
    from distributed_model_parallel_tpu.orchestrator.scheduler import (
        DevicePool,
    )
    from distributed_model_parallel_tpu.serve import (
        Engine,
        ServeConfig,
        ServeFleet,
        SimClock,
        mixed_tenants,
    )
    from distributed_model_parallel_tpu.serve.journal import RequestJournal
    from distributed_model_parallel_tpu.serve.scheduler import RequestState
    from distributed_model_parallel_tpu.utils.telemetry import (
        TelemetryRun,
        join_request_traces,
        read_records,
        registry,
    )
    from scripts.dmp_report import build_report

    n_replicas, n_cells = args.replicas, args.cells
    if n_cells < 2:
        raise SystemExit("crashrecovery needs --cells >= 2 (the crashed "
                         "replica's requests re-admit on live peers)")
    if n_replicas < n_cells:
        raise SystemExit(f"--replicas {n_replicas} < --cells {n_cells}: "
                         f"every cell needs at least one replica")

    dt = 0.02
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=128,
                                pos_embedding="rope")
    params = tfm.init_params(jax.random.key(0), cfg)
    n_slots, page, max_len = 2, 8, 64
    base = dict(n_slots=n_slots, page_size=page,
                n_pages=(n_slots + 1) * (-(-max_len // page)),
                max_seq_len=max_len, prefill_chunk=4)
    serve = ServeConfig(**base)         # no deadlines: everything lands
    trace = mixed_tenants(seed, horizon_s=3.0, tenants={
        # Same standing load as failover, with LONGER generations: the
        # crash at round 60 (1.2 virtual s) provably catches residents
        # mid-decode, and the journal's one-terminal-fsync-per-request
        # cost amortizes over a production-shaped decode length (the
        # overhead gate measures fsyncs against real decode work, not
        # the traffic module's few-token toy defaults).
        "web": {"rate": 22.0, "priority": "interactive",
                "gen": (18, 30)},
        "mobile": {"rate": 12.0, "priority": "interactive",
                   "gen": (18, 30)},
        "etl": {"rate": 10.0, "priority": "batch", "gen": (24, 36)},
    })
    all_rids = {r["rid"] for r in trace}

    os.makedirs(workdir, exist_ok=True)
    t0 = time.monotonic()

    # -- reference: bitwise per-request tokens, one clean engine
    ref_eng = Engine(params, cfg, ServeConfig(**base), slo_metrics=False)
    ref_eng.warmup()
    ref_reqs = [ref_eng.submit(r["prompt"], r["max_new"], rid=r["rid"],
                               seed=r["seed"]) for r in trace]
    ref_eng.run()
    bad_ref = [q.rid for q in ref_reqs
               if q.state is not RequestState.COMPLETED]
    if bad_ref:
        raise RuntimeError(f"reference run failed requests: {bad_ref}")
    reference = {q.rid: list(q.generated) for q in ref_reqs}

    def run_fleet(stream, label, *, journal=None, faults_=(),
                  revive=None, max_rounds=20000):
        tel = TelemetryRun(stream, run=label)
        fleet = ServeFleet(
            params, cfg, serve, n_replicas,
            pool=DevicePool([_FakeDev(i) for i in range(n_replicas)]),
            telemetry=tel, cells=n_cells, router_seed=seed,
            clock=SimClock(dt), faults=faults_, revive_after=revive,
            journal=journal)
        for r in trace:
            fleet.submit(r["prompt"], r["max_new"], rid=r["rid"],
                         arrival_s=r["arrival_s"], seed=r["seed"],
                         priority=r["priority"], tenant=r.get("tenant"))
        # Intent records are written inside submit() — admission-path
        # latency, not serve-loop overhead. Snapshot the split so the
        # overhead gate charges the serve loop only for what rides it
        # (watermarks + terminals).
        admit_write_s = journal.write_s if journal is not None else 0.0
        s = fleet.run(max_rounds=max_rounds)
        tel.finish()
        fleet.close()
        return fleet, s, admit_write_s

    def parity_bad(fleets):
        """Rids not completed bitwise-identical to the reference across
        the given fleets (a rid counts once it completes anywhere)."""
        done = {}
        for fl in fleets:
            for q in fl.results():
                if q.state is RequestState.COMPLETED:
                    done.setdefault(q.rid, q)
        missing = sorted(all_rids - set(done))
        wrong = sorted(r for r, q in done.items()
                       if q.generated != reference[r])
        return missing + wrong

    def recovered_hops(traces):
        return sum(1 for t in traces.values()
                   for h in t["hops"] if h.get("recovered"))

    # -- journal-off vs journal-on: zero behavior change + overhead
    off_stream = os.path.join(workdir, "crashrecovery_off.jsonl")
    off_fleet, off_sum, _ = run_fleet(off_stream, "crashrecovery-off")
    off_digest = _schedule_digest(read_records(off_stream))

    on_stream = os.path.join(workdir, "crashrecovery_on.jsonl")
    j_on = RequestJournal(os.path.join(workdir, "journal_on.jsonl"))
    on_fleet, on_sum, admit_write_s = run_fleet(
        on_stream, "crashrecovery-on", journal=j_on)
    on_digest = _schedule_digest(read_records(on_stream))
    iter_wall = sum(sum(rep.engine._iter_s) for rep in on_fleet.replicas)
    serve_write_s = j_on.write_s - admit_write_s
    overhead_fraction = serve_write_s / max(iter_wall, 1e-9)
    clean_bad = parity_bad([off_fleet]) + parity_bad([on_fleet])
    st_on = j_on.state()

    # -- crash drill, twice at the same seed (digest determinism)
    def crash_drill(tag):
        j = RequestJournal(os.path.join(workdir,
                                        f"journal_crash_{tag}.jsonl"))
        stream = os.path.join(workdir, f"crashrecovery_crash_{tag}.jsonl")
        fleet, s, _ = run_fleet(stream, f"crashrecovery-crash-{tag}",
                                journal=j,
                                faults_=("crash_replica@60",), revive=45)
        return fleet, s, j, stream

    fleet_a, sum_a, j_a, stream_a = crash_drill("a")
    fleet_b, sum_b, _, stream_b = crash_drill("b")
    recs_a = read_records(stream_a)
    print(build_report(recs_a))
    digest_a = _schedule_digest(recs_a)
    digest_b = _schedule_digest(read_records(stream_b))
    crash_bad = parity_bad([fleet_a])
    crash_traces = join_request_traces(recs_a)
    crash_orphans = sorted(t["trace"] for t in crash_traces.values()
                           if t["orphan"])
    st_a = j_a.state()

    # -- restart drill: abandon mid-trace, torn tail, recover from disk
    rst_journal = os.path.join(workdir, "journal_restart.jsonl")
    rst_stream1 = os.path.join(workdir, "crashrecovery_restart_a.jsonl")
    rst_stream2 = os.path.join(workdir, "crashrecovery_restart_b.jsonl")
    j1 = RequestJournal(rst_journal)
    fleet1, _, _ = run_fleet(rst_stream1, "crashrecovery-restart-pre",
                             journal=j1, max_rounds=60)
    in_flight = sorted(q.rid for q in fleet1.results()
                       if q.state is not RequestState.COMPLETED)
    # The abandonment: fleet1 and j1 are dropped on the floor — no
    # drain, no watermark flush (j1's buffered tokens die with "the
    # process") — and the journal's live file gets a torn partial line,
    # exactly what a crash inside an append leaves behind.
    with open(rst_journal, "a") as f:
        f.write('{"ts": 0, "kind": "watermark", "rid": "torn-tail", "to')
    torn0 = registry().counter("telemetry_torn_lines").value
    j2 = RequestJournal(rst_journal)    # reopen folds disk, skips tear
    torn_counted = (registry().counter("telemetry_torn_lines").value
                    > torn0)
    tel2 = TelemetryRun(rst_stream2, run="crashrecovery-restart-post")
    fleet2 = ServeFleet.recover(
        params, cfg, serve, n_replicas, journal=j2, telemetry=tel2,
        pool=DevicePool([_FakeDev(i) for i in range(n_replicas)]),
        cells=n_cells, router_seed=seed, clock=SimClock(dt))
    rst_sum = fleet2.run(max_rounds=20000)
    tel2.finish()
    fleet2.close()
    rst_bad = parity_bad([fleet1, fleet2])
    st_rst = j2.state()
    rst_traces = join_request_traces(read_records(rst_stream1)
                                     + read_records(rst_stream2))
    rst_orphans = sorted(t["trace"] for t in rst_traces.values()
                         if t["orphan"])

    out = {
        "soak": "crashrecovery-campaign",
        "scenario": "crashrecovery",
        "seed": seed,
        "wall_s": round(time.monotonic() - t0, 1),
        "replicas": n_replicas,
        "requests": len(trace),
        "digest_off": off_digest,
        "digest_on": on_digest,
        "journal_transparent": off_digest["sha256"] == on_digest["sha256"],
        "journal_write_s": round(j_on.write_s, 4),
        "journal_admission_write_s": round(admit_write_s, 4),
        "journal_serve_write_s": round(serve_write_s, 4),
        "engine_iteration_s": round(iter_wall, 4),
        "journal_overhead_fraction": round(overhead_fraction, 5),
        "clean_parity_bad": clean_bad,
        "crash_fired": sum_a["replica_crashes"],
        "crash_recovered": sum_a["crash_recovered"],
        "crash_failed": sum_a["requests_failed"],
        "crash_parity_bad": crash_bad,
        "crash_rtrace_timelines": len(crash_traces),
        "crash_rtrace_orphans": crash_orphans,
        "crash_recovered_hops": recovered_hops(crash_traces),
        "crash_terminals": len(st_a.terminals),
        "crash_pending_after": st_a.pending(),
        "replay_deterministic": digest_a["sha256"] == digest_b["sha256"],
        "recovery_time_s": sum_a["recovery_time_s"],
        "restart_in_flight": len(in_flight),
        "restart_torn_line_counted": torn_counted,
        "restart_recovered": rst_sum["crash_recovered"],
        "restart_failed": rst_sum["requests_failed"],
        "restart_parity_bad": rst_bad,
        "restart_rtrace_timelines": len(rst_traces),
        "restart_rtrace_orphans": rst_orphans,
        "restart_recovered_hops": recovered_hops(rst_traces),
        "restart_terminals": len(st_rst.terminals),
        "restart_pending_after": st_rst.pending(),
        "telemetry": [off_stream, on_stream, stream_a, stream_b,
                      rst_stream1, rst_stream2],
    }
    ok = (
        # zero behavior change: journal on/off schedules byte-identical
        out["journal_transparent"]
        # journal overhead < 3% of serve iteration time
        and overhead_fraction < 0.03
        and not clean_bad
        and off_sum["requests_failed"] == 0
        and on_sum["requests_failed"] == 0
        and len(st_on.terminals) == len(trace)
        # the crash provably fired and every request recovered bitwise
        and sum_a["replica_crashes"] >= 1
        and sum_a["crash_recovered"] >= 1
        and sum_a["requests_failed"] == 0
        and sum_b["requests_failed"] == 0
        and not crash_bad
        # exactly one terminal per trace, none pending
        and len(st_a.terminals) == len(trace)
        and not st_a.pending()
        # the crash hop is a LINKED hop in a complete timeline
        and len(crash_traces) == len(trace)
        and not crash_orphans
        and recovered_hops(crash_traces) >= 1
        # same seed, same recovery schedule
        and out["replay_deterministic"]
        # the restart provably had work to recover, tolerated the torn
        # tail, and finished every accepted request exactly once
        and len(in_flight) >= 1
        and torn_counted
        and rst_sum["crash_recovered"] >= 1
        and rst_sum["requests_failed"] == 0
        and not rst_bad
        and len(st_rst.terminals) == len(trace)
        and not st_rst.pending()
        and len(rst_traces) == len(trace)
        and not rst_orphans
        and recovered_hops(rst_traces) >= 1)
    return out, ok


def run_long(args, workdir: str) -> tuple[dict, bool]:
    """Long mode: campaign after campaign with derived seeds until the
    wall-clock budget is spent; one failure fails the soak. At least one
    campaign always runs (a small ``--duration-s`` is the CI-bounded
    smoke of this very loop)."""
    campaign = _campaign_fn(args.scenario)
    t0 = time.monotonic()
    campaigns, all_ok = [], True
    i = 0
    while i == 0 or time.monotonic() - t0 < args.duration_s:
        sub = os.path.join(workdir, f"campaign_{i}")
        os.makedirs(sub, exist_ok=True)
        summary, ok = campaign(args, sub, args.seed + i)
        campaigns.append({"seed": summary["seed"], "ok": ok,
                          "wall_s": summary["wall_s"],
                          "faults": summary.get("faults_injected", []),
                          "unrecovered": summary.get("unrecovered", []),
                          "unpaired": summary.get("faults_unpaired", [])})
        all_ok = all_ok and ok
        i += 1
    return ({"soak": "long", "scenario": args.scenario,
             "campaigns": campaigns, "n_campaigns": i,
             "wall_s": round(time.monotonic() - t0, 1),
             "all_ok": all_ok}, all_ok)


def _campaign_fn(scenario: str):
    if scenario in FLEET_SCENARIOS:
        return lambda args, wd, seed: run_fleet_scenario(args, wd, seed,
                                                         scenario)
    return {"degradation": run_degradation_campaign,
            "overload": run_overload_campaign,
            "xray": run_xray_campaign,
            "crashrecovery": run_crashrecovery_scenario,
            "chaos": run_campaign}[scenario]


def _gate_postmortem(args, workdir: str, summary: dict) -> None:
    """Flight-recorder drop on any scenario gate violation: dump one
    postmortem bundle (utils/flightrec.py — merged telemetry records,
    thread stacks, live spans, memory + health snapshots) under the
    campaign workdir and print its path, so a red soak in CI leaves the
    full forensic state behind, not just a JSON verdict line."""
    from distributed_model_parallel_tpu.utils import flightrec
    from distributed_model_parallel_tpu.utils.telemetry import read_records

    records = []
    for p in summary.get("telemetry", []) or []:
        try:
            records.extend(read_records(p))
        except Exception:
            pass
    path = flightrec.dump_postmortem(
        workdir, f"soak-gate-{args.scenario}", records=records)
    if path:
        print(f"postmortem bundle: {path}", flush=True)


def main(argv=None) -> int:
    args = parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="dmp_soak_")
    if args.mode == "fast":
        summary, ok = _campaign_fn(args.scenario)(args, workdir, args.seed)
    else:
        summary, ok = run_long(args, workdir)
    if not ok:
        _gate_postmortem(args, workdir, summary)
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
