#!/usr/bin/env python
"""Chaos smoke: one injected-NaN-recovers-and-finishes training loop.

Runs a tiny data-parallel CNN fit (synthetic data, CPU-friendly) with a
deterministic ``nan_loss`` fault injected at step 1 and the recovery
supervisor armed (``utils/faults.py``, ``train/resilience.py``): the guards
detect the NaN, the supervisor restores the last good checkpoint, shrinks
the LR, retries the epoch, and training completes end to end. Prints the
``dmp_report`` resilience timeline plus ONE parseable JSON summary line,
and exits non-zero if the run did not both inject and recover.

Usage:
  JAX_PLATFORMS=cpu python scripts/dmp_chaos.py [--epochs 2] \
      [--faults nan_loss@1] [--retries 2] [--lr-shrink 0.5]

This is the ``chaos`` test tier's executable recipe — see
docs/RESILIENCE.md and ``pytest -m chaos``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", default=2, type=int)
    p.add_argument("--faults", default="nan_loss@1",
                   help="fault plan, e.g. 'nan_loss@1,stall@0:0.2'")
    p.add_argument("--retries", default=2, type=int)
    p.add_argument("--lr-shrink", default=0.5, type=float)
    p.add_argument("--workdir", default=None,
                   help="log/checkpoint root (default: a fresh tmp dir)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="dmp_chaos_")

    from distributed_model_parallel_tpu.config import (
        DataConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        RecoveryConfig,
        TrainConfig,
    )
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.faults import parse_faults
    from distributed_model_parallel_tpu.utils.telemetry import read_records

    config = TrainConfig(
        model=ModelConfig(name="tinycnn"),
        data=DataConfig(name="synthetic", batch_size=32, eval_batch_size=32,
                        synthetic_train_size=96, synthetic_eval_size=32),
        optimizer=OptimizerConfig(learning_rate=0.1, warmup_steps=2),
        mesh=MeshConfig(data=1),
        epochs=args.epochs,
        check_finite_every=1,
        recovery=RecoveryConfig(max_retries=args.retries,
                                lr_shrink=args.lr_shrink,
                                faults=parse_faults(args.faults)),
        log_dir=os.path.join(workdir, "log"),
        checkpoint_dir=os.path.join(workdir, "ckpt"),
        log_every_n_steps=1000,
    )
    trainer = Trainer(config)
    history = trainer.fit()

    records = read_records(trainer.logger.jsonl_path)
    failures = [r for r in records if r.get("kind") == "failure"]
    recoveries = [r for r in records if r.get("kind") == "recovery"]

    # The report's resilience timeline for the run we just chaos-tested.
    from scripts.dmp_report import build_report

    print(build_report(records))

    summary = {
        "chaos": "injected-nan-recovers",
        "epochs_completed": len(history),
        "faults_injected": [s.kind for s in trainer.faults.fired],
        "failures_recorded": len(failures),
        "recoveries_recorded": len(recoveries),
        "retries_used": config.recovery.max_retries
        - trainer.resilience.retries_left,
        "final_lr": trainer.config.optimizer.learning_rate,
        "telemetry": trainer.logger.jsonl_path,
    }
    print(json.dumps(summary), flush=True)
    ok = (len(history) == args.epochs and trainer.faults.fired
          and failures and recoveries)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
