#!/usr/bin/env python
"""Chaos drills: injected faults must be detected, repaired, and survived.

Each scenario runs a tiny data-parallel CNN fit (synthetic data,
CPU-friendly) with a deterministic fault plan (``utils/faults.py``) and
asserts the matching detection/recovery machinery closed the loop
(``train/resilience.py``, ``train/consistency.py``). Prints the
``dmp_report`` resilience timeline plus ONE parseable JSON summary line;
exits non-zero when the fault was not injected, not detected, or not
recovered.

Scenarios (``--scenario``):

* ``nan`` (default) — injected NaN loss: guards detect, the supervisor
  restores the last good checkpoint, shrinks the LR, retries; training
  completes end to end.
* ``bitflip`` — silent data corruption: one bit flipped in ONE data
  replica's params. The consistency sentinel detects the divergence
  within one cadence, repairs by re-broadcasting from the majority-good
  replicas, and the final params must match an UNINJECTED run bitwise.
  Non-zero exit on unrepaired divergence or parity loss.
* ``desync`` — replica drift on a 2-replica mesh: both fingerprints are
  finite but disagree, so there is NO quorum; the sentinel falls back to
  the supervisor's good-slot restore and the run still completes.
* ``overhead`` — no faults: measures the sentinel's steady-state cost
  at a cadence of every 10 steps (target < 5% of step time on the CPU
  mesh). Gates on the exact ``consistency_check_s`` timings against the
  run's total step time (compile warmed up outside the window); an A/B
  sentinel-off run rides along as a diagnostic only — on a shared
  1-core host the two arms differ by 10-30% from load noise alone.
* ``preempt`` — elastic resume (train/elastic.py): a run is preempted
  mid-epoch (injected ``preempt`` fault = SIGTERM minus the signal), the
  preemption/emergency save captures the exact position, and the drill
  restarts it twice: on the SAME mesh (must reach bitwise-identical
  final params and per-step loss trajectory vs an uninterrupted run)
  and on HALF the dp degree (must continue from the exact global step
  with no sample replayed or skipped, via resharded restore). Non-zero
  exit on any violation.

Usage:
  JAX_PLATFORMS=cpu python scripts/dmp_chaos.py [--scenario nan] \
      [--epochs 2] [--faults nan_loss@1] [--retries 2] [--lr-shrink 0.5]

This is the ``chaos`` test tier's executable recipe — see
docs/RESILIENCE.md and ``pytest -m chaos``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Virtual CPU devices for the replicated-mesh scenarios (must precede any
# jax import; a no-op when the test session already forced a device count).
if (os.environ.get("JAX_PLATFORMS") == "cpu"
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="nan",
                   choices=["nan", "bitflip", "desync", "overhead",
                            "preempt"])
    p.add_argument("--epochs", default=None, type=int,
                   help="epochs per drill run (default 2; the overhead "
                        "scenario pins 1)")
    p.add_argument("--faults", default=None,
                   help="override the scenario's fault plan, e.g. "
                        "'nan_loss@1,stall@0:0.2' (nan/bitflip/desync "
                        "scenarios; overhead measures a zero-fault run)")
    p.add_argument("--retries", default=None, type=int,
                   help="recovery retry budget (default 2; overhead runs "
                        "fault-free)")
    p.add_argument("--lr-shrink", default=None, type=float,
                   help="LR shrink on non-finite recovery (nan scenario "
                        "only; default 0.5)")
    p.add_argument("--consistency-every", default=None, type=int,
                   help="sentinel cadence for bitflip/desync (default 1; "
                        "the nan scenario uses the guards and overhead "
                        "pins 10)")
    p.add_argument("--workdir", default=None,
                   help="log/checkpoint root (default: a fresh tmp dir)")
    return p.parse_args(argv)


def _config(workdir, name, **kw):
    from distributed_model_parallel_tpu.config import (
        DataConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        TrainConfig,
    )

    defaults = dict(
        model=ModelConfig(name="tinycnn"),
        data=DataConfig(name="synthetic", batch_size=32, eval_batch_size=32,
                        synthetic_train_size=96, synthetic_eval_size=32),
        optimizer=OptimizerConfig(learning_rate=0.1, warmup_steps=2),
        mesh=MeshConfig(data=1),
        log_dir=os.path.join(workdir, "log"),
        checkpoint_dir=os.path.join(workdir, f"ckpt_{name}"),
        log_every_n_steps=1000,
    )
    defaults.update(kw)
    defaults["log_name"] = name
    return TrainConfig(**defaults)


def _events(records):
    return ([r for r in records if r.get("kind") == "failure"],
            [r for r in records if r.get("kind") == "recovery"],
            [r for r in records if r.get("kind") == "consistency"])


def _report(trainer):
    from distributed_model_parallel_tpu.utils.telemetry import read_records
    from scripts.dmp_report import build_report

    records = read_records(trainer.logger.jsonl_path)
    print(build_report(records))
    return records


def _data_width(n_dev: int) -> int:
    """Largest power of two <= min(8, n_dev): always divides the batch
    size of 32, unlike a raw device count of e.g. 3 or 6."""
    w = 1
    while w * 2 <= min(8, n_dev):
        w *= 2
    return w


def _bitwise_equal(tree_a, tree_b) -> bool:
    import jax
    import numpy as np

    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(la, lb))


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def scenario_nan(args, workdir) -> tuple[dict, bool]:
    """Injected NaN -> guards detect -> restore + LR shrink -> finish."""
    from distributed_model_parallel_tpu.config import RecoveryConfig
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.faults import parse_faults

    config = _config(
        workdir, "chaos_nan", epochs=args.epochs, check_finite_every=1,
        recovery=RecoveryConfig(max_retries=args.retries,
                                lr_shrink=args.lr_shrink,
                                faults=parse_faults(args.faults
                                                    or "nan_loss@1")))
    trainer = Trainer(config)
    history = trainer.fit()
    failures, recoveries, _ = _events(_report(trainer))
    summary = {
        "chaos": "injected-nan-recovers",
        "epochs_completed": len(history),
        "faults_injected": [s.kind for s in trainer.faults.fired],
        "failures_recorded": len(failures),
        "recoveries_recorded": len(recoveries),
        "retries_used": config.recovery.max_retries
        - trainer.resilience.retries_left,
        "final_lr": trainer.config.optimizer.learning_rate,
        "telemetry": trainer.logger.jsonl_path,
    }
    ok = bool(len(history) == args.epochs and trainer.faults.fired
              and failures and recoveries)
    return summary, ok


def scenario_bitflip(args, workdir) -> tuple[dict, bool]:
    """Silent bitflip in one replica -> sentinel detects within one
    cadence -> re-broadcast repair -> final params bitwise-match an
    uninjected run."""
    import jax

    from distributed_model_parallel_tpu.config import RecoveryConfig
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.faults import parse_faults

    n_dev = len(jax.devices())
    if n_dev < 4:
        # A repair quorum needs a strict majority (>= 3 replicas) and the
        # data width must divide batch 32 — the smallest such width is 4.
        print(f"bitflip scenario needs >= 4 devices for a repair quorum, "
              f"have {n_dev}", file=sys.stderr)
        return {"chaos": "bitflip", "error": "needs >= 4 devices"}, False
    from distributed_model_parallel_tpu.config import MeshConfig

    kw = dict(
        epochs=args.epochs, mesh=MeshConfig(data=_data_width(n_dev)),
        # None -> default cadence 1; an EXPLICIT 0 flows through so the
        # supervisor's corruption-without-sentinel rejection fires loudly
        # instead of the drill silently re-arming the sentinel.
        consistency_every=(1 if args.consistency_every is None
                           else args.consistency_every),
        # Drain every step so the sentinel sees the corruption before the
        # next dispatch consumes it — required for bitwise parity.
        max_inflight_steps=1, log_every_n_steps=1)
    clean = Trainer(_config(workdir, "chaos_bitflip_clean",
                            recovery=RecoveryConfig(max_retries=1), **kw))
    clean.fit()
    injected = Trainer(_config(
        workdir, "chaos_bitflip",
        recovery=RecoveryConfig(max_retries=args.retries,
                                faults=parse_faults(args.faults
                                                    or "bitflip@1")),
        **kw))
    history = injected.fit()
    records = _report(injected)
    failures, recoveries, consistency = _events(records)
    statuses = [c.get("status") for c in consistency]
    parity = _bitwise_equal(jax.device_get(clean.state.params),
                            jax.device_get(injected.state.params))
    summary = {
        "chaos": "bitflip-detected-repaired-parity",
        "epochs_completed": len(history),
        "faults_injected": [s.kind for s in injected.faults.fired],
        "consistency": statuses,
        "repairs": injected.sentinel.repairs,
        "recoveries": [r.get("action") for r in recoveries],
        "bitwise_parity_with_clean_run": parity,
        "telemetry": injected.logger.jsonl_path,
    }
    ok = bool(len(history) == args.epochs and injected.faults.fired
              and "divergence" in statuses and "repaired" in statuses
              and "replica-rebroadcast" in summary["recoveries"]
              and parity)
    return summary, ok


def scenario_desync(args, workdir) -> tuple[dict, bool]:
    """Finite 1-vs-1 drift -> no quorum -> good-slot restore -> finish."""
    import jax

    from distributed_model_parallel_tpu.config import (
        MeshConfig,
        RecoveryConfig,
    )
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.faults import parse_faults

    if len(jax.devices()) < 2:
        print("desync scenario needs >= 2 devices", file=sys.stderr)
        return {"chaos": "desync", "error": "needs >= 2 devices"}, False
    trainer = Trainer(_config(
        workdir, "chaos_desync", epochs=args.epochs,
        mesh=MeshConfig(data=2),
        consistency_every=(1 if args.consistency_every is None
                           else args.consistency_every),
        max_inflight_steps=1, log_every_n_steps=1,
        recovery=RecoveryConfig(max_retries=args.retries,
                                faults=parse_faults(args.faults
                                                    or "desync@1"))))
    history = trainer.fit()
    failures, recoveries, consistency = _events(_report(trainer))
    statuses = [c.get("status") for c in consistency]
    summary = {
        "chaos": "desync-no-quorum-good-slot-restore",
        "epochs_completed": len(history),
        "faults_injected": [s.kind for s in trainer.faults.fired],
        "consistency": statuses,
        "failures": [f.get("error") for f in failures],
        "recoveries": [r.get("action") for r in recoveries],
        "telemetry": trainer.logger.jsonl_path,
    }
    ok = bool(len(history) == args.epochs and trainer.faults.fired
              and "no-quorum" in statuses
              and "replica-divergence" in summary["failures"]
              and "restored" in summary["recoveries"])
    return summary, ok


def scenario_overhead(args, workdir) -> tuple[dict, bool]:
    """Measure the sentinel's step-time cost at cadence 10 vs off."""
    import jax

    from distributed_model_parallel_tpu.config import (
        DataConfig,
        MeshConfig,
        RecoveryConfig,
    )
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.telemetry import read_records

    mesh = MeshConfig(data=_data_width(len(jax.devices())))
    data = DataConfig(name="synthetic", batch_size=32, eval_batch_size=32,
                      synthetic_train_size=1024, synthetic_eval_size=32)

    from distributed_model_parallel_tpu.utils.telemetry import registry

    def run(name, every):
        t = Trainer(_config(
            workdir, name, epochs=1, mesh=mesh, data=data,
            consistency_every=every, max_inflight_steps=1,
            log_every_n_steps=1, recovery=RecoveryConfig()))
        if every:
            # Warm the sentinel's jitted fingerprint program outside the
            # measured window: the criterion is the steady-state cost of
            # a cadence-10 check, and the one-time shard_map compile
            # (seconds on this 1-core host) would otherwise be billed to
            # the first cadence window.
            t.sentinel.check(t._sentinel_tree())
        hist = registry().histogram("consistency_check_s")
        pre_sum, pre_count = hist.sum, hist.count
        t.fit()
        recs = read_records(t.logger.jsonl_path)
        times = [r["step_time_s"] for r in recs if r.get("kind") == "step"
                 and isinstance(r.get("step_time_s"), (int, float))][1:]
        mean = sum(times) / max(len(times), 1)
        return (mean, len(times), hist.sum - pre_sum,
                hist.count - pre_count)

    mean_off, n_off, _, _ = run("chaos_overhead_off", 0)
    mean_on, n_on, check_s, n_checks = run("chaos_overhead_on", 10)
    # Gating metric: the sentinel's own per-check timings (the exact
    # consistency_check_s histogram delta over the measured run) against
    # the run's total step time — immune to the run-to-run load noise of
    # this shared 1-core host. The A/B step-time means stay as a
    # diagnostic: a p50 would never even see the 1-in-cadence windows
    # that pay the check, and on this host the two arms routinely differ
    # by 10-30% from machine noise alone, so neither is fit to gate on.
    total_on = mean_on * n_on
    overhead_pct = check_s / max(total_on - check_s, 1e-12) * 100.0
    ab_pct = (mean_on - mean_off) / max(mean_off, 1e-12) * 100.0
    summary = {
        "chaos": "sentinel-overhead",
        "cadence": 10,
        "steps_measured": [n_off, n_on],
        "consistency_checks": n_checks,
        "check_time_s": {"total": check_s,
                         "mean": check_s / max(n_checks, 1)},
        "overhead_pct": round(overhead_pct, 2),
        "target_pct": 5.0,
        "within_target": overhead_pct < 5.0,
        "step_time_mean_s_ab_diagnostic": {"sentinel_off": mean_off,
                                           "sentinel_on": mean_on,
                                           "delta_pct": round(ab_pct, 2)},
    }
    # Measurement scenario: report honestly, never flake CI on wall clock.
    return summary, bool(n_off and n_on and n_checks)


def _per_step_losses(records) -> dict:
    """Reconstruct per-step losses from the window-averaged ``step``
    telemetry records of a ``log_every_n_steps=1`` run: with equal batch
    sizes the epoch meter is an arithmetic running mean, so
    ``loss_k = avg_k * k - avg_{k-1} * (k-1)`` (k = records seen this
    epoch *in this run* — a resumed run's partial epoch starts a fresh
    meter). Keys are ``(epoch, step)``; the step field is the global batch
    index within the epoch, so baseline and resumed runs align."""
    from collections import defaultdict

    by_epoch = defaultdict(list)
    for r in records:
        if r.get("kind") == "step" and isinstance(r.get("loss"),
                                                  (int, float)):
            by_epoch[r["epoch"]].append((r["step"], r["loss"]))
    out = {}
    for ep, lst in by_epoch.items():
        lst.sort()
        prev_sum = 0.0
        for k, (step, avg) in enumerate(lst, start=1):
            out[(ep, step)] = avg * k - prev_sum
            prev_sum = avg * k
    return out


def scenario_preempt(args, workdir) -> tuple[dict, bool]:
    """Kill mid-epoch -> exact-step resume (same mesh: bitwise parity;
    halved dp: exact continuation, nothing replayed or skipped)."""
    import shutil

    import jax
    import numpy as np

    from distributed_model_parallel_tpu.config import (
        MeshConfig,
        RecoveryConfig,
    )
    from distributed_model_parallel_tpu.train.trainer import Trainer
    from distributed_model_parallel_tpu.utils.faults import parse_faults
    from distributed_model_parallel_tpu.utils.telemetry import read_records

    if len(jax.devices()) < 4:
        print("preempt scenario needs >= 4 devices (dp=4 halved to dp=2)",
              file=sys.stderr)
        return {"chaos": "preempt", "error": "needs >= 4 devices"}, False
    steps_per_epoch = 96 // 32        # _config's synthetic set / batch
    total_steps = args.epochs * steps_per_epoch
    # Fire after the 2nd step of the FINAL epoch: unambiguously mid-epoch.
    kill_at = steps_per_epoch * (args.epochs - 1) + 1
    kw = dict(epochs=args.epochs, mesh=MeshConfig(data=4),
              max_inflight_steps=1, log_every_n_steps=1, emergency_every=2)

    baseline = Trainer(_config(workdir, "chaos_preempt_base",
                               recovery=RecoveryConfig(), **kw))
    baseline.fit()
    base_losses = _per_step_losses(read_records(baseline.logger.jsonl_path))

    plan = parse_faults(args.faults or f"preempt@{kill_at}")
    killed = Trainer(_config(workdir, "chaos_preempt_kill",
                             recovery=RecoveryConfig(faults=plan), **kw))
    killed.fit()
    killed_pos = killed.train_loader.state_dict()
    killed_step = killed._global_step
    ck_dir = killed.config.checkpoint_dir
    half_dir = ck_dir + "_half"
    shutil.copytree(ck_dir, half_dir)   # same-mesh arm mutates the slots

    # Restart 1: same mesh — must converge bitwise-identically to the
    # uninterrupted run, with the resumed steps' losses on its trajectory.
    r1 = Trainer(_config(workdir, "chaos_preempt_resume",
                         recovery=RecoveryConfig(), checkpoint_dir=ck_dir,
                         resume=True, **kw))
    r1_pos = dict(epoch=r1.train_loader.epoch,
                  cursor=r1.train_loader.cursor,
                  global_step=r1._global_step)
    r1.fit()
    r1_records = read_records(r1.logger.jsonl_path)
    r1_losses = _per_step_losses(r1_records)
    traj_ok = bool(r1_losses) and all(
        key in base_losses and np.isclose(base_losses[key], loss,
                                          rtol=1e-5, atol=1e-6)
        for key, loss in r1_losses.items())
    parity = (_bitwise_equal(jax.device_get(baseline.state.params),
                             jax.device_get(r1.state.params))
              and int(jax.device_get(r1.state.step)) == total_steps)

    # Restart 2: half the dp degree (the degraded slice a preempted TPU
    # job typically gets back) — resharded restore, exact-step
    # continuation, no sample replayed or skipped.
    r2 = Trainer(_config(workdir, "chaos_preempt_resume_half",
                         recovery=RecoveryConfig(), checkpoint_dir=half_dir,
                         resume=True, **{**kw, "mesh": MeshConfig(data=2)}))
    r2_pos = dict(epoch=r2.train_loader.epoch,
                  cursor=r2.train_loader.cursor,
                  global_step=r2._global_step)
    r2.fit()
    half_ok = (r2_pos == r1_pos
               and int(jax.device_get(r2.state.step)) == total_steps
               and r2._global_step - r2_pos["global_step"]
               == total_steps - killed_step)

    _report(r1)
    resume_recs = [r for r in r1_records if r.get("kind") == "resume"]
    summary = {
        "chaos": "preempt-exact-resume",
        "faults_injected": [s.kind for s in killed.faults.fired],
        "killed_at": {"global_step": killed_step, **killed_pos},
        "emergency_saves": killed.emergency.saves,
        "resumed_at_same_mesh": r1_pos,
        "resumed_at_half_dp": r2_pos,
        "resume_records": [r.get("slot") for r in resume_recs],
        "bitwise_parity_with_uninterrupted": parity,
        "loss_trajectory_parity": traj_ok,
        "half_dp_exact_continuation": half_ok,
        "telemetry": r1.logger.jsonl_path,
    }
    ok = bool(killed.faults.fired
              and killed_step == kill_at + 1       # stopped right after
              and killed_pos["batch_cursor"] != 0  # genuinely mid-epoch
              and r1_pos["global_step"] == killed_step
              and r1_pos["cursor"] == killed_pos["batch_cursor"]
              and resume_recs and parity and traj_ok and half_ok)
    return summary, ok


SCENARIOS = {
    "nan": scenario_nan,
    "bitflip": scenario_bitflip,
    "desync": scenario_desync,
    "overhead": scenario_overhead,
    "preempt": scenario_preempt,
}


def main(argv=None) -> int:
    args = parse_args(argv)
    # No silent ignores: reject overrides the chosen scenario never reads.
    unread = {
        "overhead": [("--faults", args.faults),
                     ("--consistency-every", args.consistency_every),
                     ("--epochs", args.epochs), ("--retries", args.retries),
                     ("--lr-shrink", args.lr_shrink)],
        "nan": [("--consistency-every", args.consistency_every)],
        "bitflip": [("--lr-shrink", args.lr_shrink)],
        "desync": [("--lr-shrink", args.lr_shrink)],
        "preempt": [("--consistency-every", args.consistency_every),
                    ("--retries", args.retries),
                    ("--lr-shrink", args.lr_shrink)],
    }[args.scenario]
    bad = [flag for flag, value in unread if value is not None]
    if bad:
        print(f"{', '.join(bad)} has no effect on the {args.scenario} "
              f"scenario (see --help for which flags each scenario reads)",
              file=sys.stderr)
        return 2
    if args.scenario == "bitflip" and (args.consistency_every or 0) > 1:
        # An explicit 0 still flows through (the supervisor's corruption-
        # without-sentinel rejection fires loudly); >1 is rejected because
        # the steps between corruption and the next check fold the bad
        # replica's gradients into everyone via the allreduce, so repair
        # restores consistency to an already-drifted state and the drill's
        # bitwise-parity gate can never pass — a false "unrepaired" exit 1.
        print("--consistency-every > 1 cannot satisfy the bitflip drill's "
              "bitwise-parity gate (corrupted gradients reach the allreduce "
              "before the next check); use the default cadence 1, or the "
              "overhead scenario to measure cadence cost", file=sys.stderr)
        return 2
    if args.scenario == "desync" and args.retries is not None \
            and args.retries < 1:
        print("--retries 0 disables recovery, but the desync drill exists "
              "to demonstrate the no-quorum -> good-slot-restore fallback; "
              "use the trainers directly to observe the fail-fast path",
              file=sys.stderr)
        return 2
    args.epochs = 2 if args.epochs is None else args.epochs
    args.retries = 2 if args.retries is None else args.retries
    args.lr_shrink = 0.5 if args.lr_shrink is None else args.lr_shrink
    workdir = args.workdir or tempfile.mkdtemp(prefix="dmp_chaos_")
    summary, ok = SCENARIOS[args.scenario](args, workdir)
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
