#!/usr/bin/env python
"""dmp_xray — per-request fleet X-ray over rtrace telemetry streams.

Reconstructs causally ordered per-request timelines from the ``rtrace``
records the serving tier emits (router admission, queue wait, brownout
clamps, prefill chunks, decode rounds with memory gauges, migration
export/import hops, crash-recovery ``recovered`` hops, terminal events)
and renders them three ways:

* fleet summary (default) — trace counts, completion/orphan rates,
  terminal-event breakdown, migration hops;
* ``--trace ID`` / ``--request RID`` — a single-request waterfall with
  per-event deltas and phase attribution;
* ``--worst K --metric ttft|tbt|queue_wait`` — exemplar report: the K
  worst requests by the chosen metric, each with its phase breakdown
  (queue / prefill / decode / brownout-clamp / migration-pause /
  memory-stall).

Usage:
    python scripts/dmp_xray.py /tmp/run/serve.jsonl
    python scripts/dmp_xray.py a.jsonl b.jsonl --timeline
    python scripts/dmp_xray.py serve.jsonl --trace 1f03-2
    python scripts/dmp_xray.py serve.jsonl --worst 5 --metric ttft
    python scripts/dmp_xray.py serve.jsonl --gate --json

``--gate`` exits non-zero when any timeline is orphaned (seq gap, no
terminal, or multiple terminals) or when a timeline's per-phase seconds
disagree with its measured wall time by more than 5% — the soak-drill
acceptance check.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    join_request_traces,
    read_records,
)

# Phase names in render order (matches utils.telemetry._rtrace_phase).
PHASES = ("queue", "prefill", "decode", "brownout-clamp",
          "migration-pause", "memory-stall", "other")

METRICS = ("ttft", "tbt", "queue_wait")


def load_traces(paths: list[str]) -> dict[str, dict]:
    """Read every stream (rotated parts fold in automatically), stamp
    each rtrace record with its source file's basename as the ``stream``
    tag — the hop-origin fallback when replicas write separate files —
    and join into per-request timelines."""
    records: list[dict] = []
    for path in paths:
        tag = os.path.basename(path)
        for rec in read_records(path):
            if rec.get("kind") == "rtrace":
                rec.setdefault("stream", tag)
            records.append(rec)
    return join_request_traces(records)


def _event_field(tl: dict, event: str, field: str):
    """First occurrence of ``field`` on an event named ``event``."""
    for r in tl["events"]:
        if r.get("event") == event and r.get(field) is not None:
            return r[field]
    return None


def _event_ts(tl: dict, event: str):
    for r in tl["events"]:
        if r.get("event") == event and isinstance(r.get("ts"), (int, float)):
            return r["ts"]
    return None


def metric_value(tl: dict, metric: str) -> float | None:
    """Extract the ranking metric for ``--worst`` from a timeline,
    preferring the measured fields the engine stamped on the records and
    falling back to timestamp deltas."""
    if metric == "ttft":
        v = _event_field(tl, "completed", "ttft_s")
        if v is None:
            v = _event_field(tl, "prefill", "ttft_s")
        if v is None:
            t_dec, t0 = _event_ts(tl, "decode"), tl.get("t0")
            if t_dec is not None and t0 is not None:
                v = t_dec - t0
        return None if v is None else float(v)
    if metric == "tbt":
        v = _event_field(tl, "completed", "token_latency_s")
        if v is None:
            n = _event_field(tl, "completed", "new_tokens")
            if n and tl.get("wall_s"):
                v = tl["wall_s"] / float(n)
        return None if v is None else float(v)
    if metric == "queue_wait":
        v = _event_field(tl, "completed", "queue_wait_s")
        if v is None:
            t_adm, t0 = _event_ts(tl, "admitted"), tl.get("t0")
            if t_adm is not None and t0 is not None:
                v = t_adm - t0
        return None if v is None else float(v)
    raise ValueError(f"unknown metric {metric!r}")


def phase_gate_error(tl: dict) -> float:
    """Relative disagreement between the per-phase seconds and the
    timeline's measured wall time (0.0 when wall is ~zero — a trace
    that started and terminated inside one tick attributes nothing)."""
    wall = tl.get("wall_s") or 0.0
    total = sum(tl.get("phases", {}).values())
    if wall <= 1e-9:
        return 0.0 if total <= 1e-9 else 1.0
    return abs(total - wall) / wall


def summarize(traces: dict[str, dict]) -> dict:
    terminals: dict[str, int] = {}
    orphans = hops = crash_hops = 0
    phase_totals = {p: 0.0 for p in PHASES}
    for tl in traces.values():
        if tl["orphan"]:
            orphans += 1
        if tl["terminal"]:
            terminals[tl["terminal"]] = terminals.get(tl["terminal"], 0) + 1
        hops += len(tl["hops"])
        crash_hops += sum(1 for h in tl["hops"] if h.get("recovered"))
        for p, s in tl["phases"].items():
            phase_totals[p] = phase_totals.get(p, 0.0) + s
    n = len(traces)
    return {
        "traces": n,
        "complete": n - orphans,
        "orphans": orphans,
        "terminals": dict(sorted(terminals.items())),
        "migration_hops": hops,
        "recovered_hops": crash_hops,
        "phase_seconds": {p: round(s, 4)
                         for p, s in phase_totals.items() if s > 0},
    }


def _fmt_phases(phases: dict[str, float]) -> str:
    parts = [f"{p}={phases[p]:.4f}s" for p in PHASES if phases.get(p)]
    return " ".join(parts) if parts else "(instantaneous)"


def render_summary(traces: dict[str, dict], out) -> None:
    s = summarize(traces)
    print("== fleet x-ray ==", file=out)
    print(f"traces: {s['traces']}  complete: {s['complete']}  "
          f"orphans: {s['orphans']}  migration hops: "
          f"{s['migration_hops']}  recovered hops: "
          f"{s['recovered_hops']}", file=out)
    if s["terminals"]:
        terms = "  ".join(f"{k}={v}" for k, v in s["terminals"].items())
        print(f"terminals: {terms}", file=out)
    if s["phase_seconds"]:
        print(f"fleet phase seconds: {_fmt_phases(s['phase_seconds'])}",
              file=out)
    for tl in traces.values():
        if tl["orphan"]:
            print(f"  ORPHAN {tl['trace']} (request={tl['request']}): "
                  f"{', '.join(tl['orphan_reasons'])}", file=out)


def render_waterfall(tl: dict, out) -> None:
    print(f"== request waterfall: trace={tl['trace']} "
          f"request={tl['request']} ==", file=out)
    term = tl["terminal"] or "NONE"
    print(f"terminal: {term}  wall: {tl['wall_s']:.4f}s  "
          f"hops: {len(tl['hops'])}"
          + (f"  ORPHAN: {', '.join(tl['orphan_reasons'])}"
             if tl["orphan"] else ""), file=out)
    t0 = tl.get("t0")
    prev_ts = None
    for r in tl["events"]:
        ts = r.get("ts")
        rel = (ts - t0) if isinstance(ts, (int, float)) \
            and t0 is not None else None
        dt = (ts - prev_ts) if isinstance(ts, (int, float)) \
            and prev_ts is not None else None
        if isinstance(ts, (int, float)):
            prev_ts = ts
        origin = r.get("replica") or r.get("stream") or "-"
        extras = {k: v for k, v in r.items()
                  if k not in ("ts", "kind", "trace", "seq", "request",
                               "event", "replica", "stream", "run",
                               "tenant")}
        detail = " ".join(f"{k}={v}" for k, v in extras.items())
        rel_s = f"{rel:+.4f}s" if rel is not None else "   ?   "
        dt_s = f"(+{dt:.4f}s)" if dt is not None else ""
        print(f"  [{r.get('seq'):>3}] {rel_s} {dt_s:>12} "
              f"{r.get('event'):<13} @{origin:<8} {detail}", file=out)
    for hop in tl["hops"]:
        tag = " (crash recovery)" if hop.get("recovered") else ""
        print(f"  hop @seq {hop['seq']}: {hop['from'] or '?'} -> "
              f"{hop['to'] or '?'}{tag}", file=out)
    print(f"  phases: {_fmt_phases(tl['phases'])}", file=out)


def render_timeline(traces: dict[str, dict], out) -> None:
    """Fleet timeline: every event from every trace, wall-clock ordered,
    with per-trace seq preserved in the row."""
    rows = []
    for tl in traces.values():
        for r in tl["events"]:
            ts = r.get("ts")
            rows.append((ts if isinstance(ts, (int, float)) else 0.0,
                         tl["trace"], r))
    rows.sort(key=lambda t: (t[0], t[1]))
    t0 = rows[0][0] if rows else 0.0
    print("== fleet timeline ==", file=out)
    for ts, trace, r in rows:
        origin = r.get("replica") or r.get("stream") or "-"
        print(f"  {ts - t0:+9.4f}s {trace:<14} "
              f"[{r.get('seq'):>3}] {r.get('event'):<13} @{origin}",
              file=out)


def worst_report(traces: dict[str, dict], metric: str, k: int) -> list[dict]:
    ranked = []
    for tl in traces.values():
        v = metric_value(tl, metric)
        if v is None:
            continue
        ranked.append({
            "trace": tl["trace"],
            "request": tl["request"],
            metric: round(v, 6),
            "terminal": tl["terminal"],
            "wall_s": round(tl["wall_s"], 6),
            "hops": len(tl["hops"]),
            "phases": {p: round(s, 6) for p, s in tl["phases"].items()},
        })
    ranked.sort(key=lambda d: -d[metric])
    return ranked[:k]


def render_worst(report: list[dict], metric: str, out) -> None:
    print(f"== worst {len(report)} by {metric} ==", file=out)
    for i, row in enumerate(report, 1):
        print(f"{i:>2}. {metric}={row[metric]:.4f}s  trace={row['trace']}  "
              f"request={row['request']}  terminal={row['terminal']}  "
              f"wall={row['wall_s']:.4f}s  hops={row['hops']}", file=out)
        print(f"    phases: {_fmt_phases(row['phases'])}", file=out)


def run_gate(traces: dict[str, dict], tol: float, out) -> int:
    """The soak acceptance gate: every timeline complete (no orphans)
    and every timeline's phase attribution within ``tol`` of its wall
    time. Returns a process exit code."""
    failures = []
    for tl in traces.values():
        if tl["orphan"]:
            failures.append(f"orphan trace {tl['trace']} "
                            f"({', '.join(tl['orphan_reasons'])})")
        err = phase_gate_error(tl)
        if err > tol:
            failures.append(f"phase-sum mismatch on {tl['trace']}: "
                            f"{err:.1%} > {tol:.0%}")
    if not traces:
        failures.append("no rtrace timelines found")
    for f in failures:
        print(f"GATE FAIL: {f}", file=out)
    if not failures:
        print(f"GATE OK: {len(traces)} timelines complete, phase "
              f"attribution within {tol:.0%}", file=out)
    return 1 if failures else 0


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dmp_xray",
        description="Per-request fleet X-ray over rtrace streams.")
    p.add_argument("streams", nargs="+",
                   help="telemetry stream path(s) (.jsonl; rotated parts "
                        "fold in automatically)")
    p.add_argument("--trace", default=None,
                   help="render one request's waterfall by trace id")
    p.add_argument("--request", default=None,
                   help="render one request's waterfall by request id")
    p.add_argument("--worst", type=int, default=None, metavar="K",
                   help="exemplar report: the K worst requests by --metric")
    p.add_argument("--metric", choices=METRICS, default="ttft",
                   help="ranking metric for --worst (default: ttft)")
    p.add_argument("--timeline", action="store_true",
                   help="render the wall-ordered fleet timeline")
    p.add_argument("--json", action="store_true",
                   help="emit JSON instead of text")
    p.add_argument("--gate", action="store_true",
                   help="exit non-zero on orphans or phase-sum mismatch")
    p.add_argument("--gate-tolerance", type=float, default=0.05,
                   help="relative phase-sum tolerance for --gate "
                        "(default: 0.05)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    traces = load_traces(args.streams)
    out = sys.stdout

    if args.trace is not None or args.request is not None:
        if args.trace is not None:
            tl = traces.get(str(args.trace))
        else:
            tl = next((t for t in traces.values()
                       if str(t.get("request")) == str(args.request)), None)
        if tl is None:
            print("no matching trace", file=sys.stderr)
            return 2
        if args.json:
            json.dump(tl, out, default=str)
            print(file=out)
        else:
            render_waterfall(tl, out)
        return 0

    rc = 0
    if args.json:
        payload = {"summary": summarize(traces)}
        if args.worst is not None:
            payload["worst"] = worst_report(traces, args.metric, args.worst)
        if args.timeline:
            payload["traces"] = list(traces.values())
        if args.gate:
            payload["gate_failures"] = [
                tl["trace"] for tl in traces.values()
                if tl["orphan"]
                or phase_gate_error(tl) > args.gate_tolerance]
            rc = 1 if (payload["gate_failures"] or not traces) else 0
        json.dump(payload, out, default=str)
        print(file=out)
        return rc

    render_summary(traces, out)
    if args.worst is not None:
        render_worst(worst_report(traces, args.metric, args.worst),
                     args.metric, out)
    if args.timeline:
        render_timeline(traces, out)
    if args.gate:
        rc = run_gate(traces, args.gate_tolerance, out)
    return rc


if __name__ == "__main__":
    sys.exit(main())
