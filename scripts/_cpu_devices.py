"""Pre-jax-init CPU virtual-device shim shared by the training CLIs.

When ``JAX_PLATFORMS=cpu``, create a virtual CPU device per requested
parallel rank (the test/dev story for multi-chip code, SURVEY.md §4). The
environment may import jax at interpreter startup with another platform
baked in, so the override must run before the backend initializes — hence
argv pre-parsing instead of argparse.
"""

from __future__ import annotations

import os
import sys


def _argv_value(flag: str) -> str | None:
    argv = sys.argv
    for i, a in enumerate(argv):
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
    return None


def force_cpu_devices(
        flags: tuple[str | tuple[str, ...], ...] = ("--num-devices",)) -> None:
    """Create prod(<flag values>) virtual CPU devices (no-op off-CPU or
    when the product is 1). Call at module import, before any jax use.

    Each element of ``flags`` is one factor: either a flag name or a tuple
    of argparse aliases for the *same* option (first one present in argv
    wins — aliases never multiply with each other).
    """
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    n = 1
    for flag in flags:
        aliases = (flag,) if isinstance(flag, str) else flag
        for a in aliases:
            v = _argv_value(a)
            if v and v.isdigit():
                n *= int(v)
                break
    if n > 1:
        import jax
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            # Older jax (this container's) lacks the config option; the
            # XLA_FLAGS spelling works there — but only as a fallback,
            # because a newer jax rejects having BOTH knobs set.
            flags_env = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags_env:
                os.environ["XLA_FLAGS"] = (
                    f"{flags_env} "
                    f"--xla_force_host_platform_device_count={n}").strip()
