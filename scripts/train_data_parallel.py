#!/usr/bin/env python
"""Data-parallel training driver.

CLI parity with the reference's ``data_parallel.py`` (flags ``--lr``,
``--resume``; ``data_parallel.py:19-23``) plus the knobs its pipeline script
exposed (``model_parallel.py:15-42``: dataset, batch size, workers, wd,
momentum, epochs) — all honored, none silently ignored (the reference ignores
``-b``/``-j``/``-type``, SURVEY.md §1).

Examples:
  python scripts/train_data_parallel.py --lr 0.4 --batch-size 512
  python scripts/train_data_parallel.py --resume --sync-bn --ddp
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from scripts._cpu_devices import force_cpu_devices

force_cpu_devices(("--num-devices",))

from distributed_model_parallel_tpu.config import (
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainConfig,
)
from distributed_model_parallel_tpu.mesh import best_effort_distributed_init


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("data", nargs="?", default="./data", help="dataset root")
    p.add_argument("--dataset-type", "-type", default="cifar10",
                   choices=["cifar10", "imagenet", "cub200", "place365",
                            "synthetic"])
    p.add_argument("--model", default="mobilenetv2")
    p.add_argument("--lr", default=0.4, type=float)
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture an XLA profiler trace of the run into DIR")
    p.add_argument("--device-data", action="store_true",
                   help="device-resident dataset fast path (gspmd only)")
    p.add_argument("--steps-per-dispatch", default=1, type=int,
                   help="train steps per jitted program with --device-data")
    p.add_argument("--optimizer", default="sgd",
                   choices=["sgd", "adam", "adamw", "adafactor", "lamb",
                            "lars"],
                   help="lars/lamb: layerwise-adaptive large-batch training; "
                        "adafactor: sub-linear optimizer memory")
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--wd", default=1e-4, type=float)
    p.add_argument("--epochs", default=100, type=int)
    p.add_argument("--batch-size", "-b", default=512, type=int)
    p.add_argument("--workers", "-j", default=2, type=int)
    p.add_argument("--warmup-epochs", default=10, type=int)
    p.add_argument("--ema-decay", default=None, type=float,
                   help="weight EMA decay (e.g. 0.999); eval and best-acc "
                        "selection use the averaged weights")
    p.add_argument("--accum-steps", default=1, type=int,
                   help="gradient accumulation: one optimizer update per k "
                        "batches (size-b batch at k == size-k*b batch)")
    p.add_argument("--resume", "-r", action="store_true")
    p.add_argument("--emergency-every", default=0, type=int, metavar="N",
                   help="elastic resume: write the emergency checkpoint "
                        "slot (full mid-epoch resume state: loader cursor, "
                        "global step, recovery budgets) every N steps so a "
                        "preempted run continues at the exact step "
                        "(0 = only the preemption save; train/elastic.py)")
    p.add_argument("--elastic", action="store_true",
                   help="on startup, shrink the data axis to the largest "
                        "degree the live device count and batch size allow "
                        "(degraded-slice restart) and reshard the resumed "
                        "checkpoint onto the rebuilt mesh")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="persist checkpoints on a background thread")
    p.add_argument("--sync-bn", action="store_true",
                   help="SyncBatchNorm semantics (BASELINE config 3)")
    p.add_argument("--no-bn", action="store_true",
                   help="train without BatchNorm (the reference's "
                        "MobileNetV2_nobn large-batch study)")
    p.add_argument("--ddp", action="store_true",
                   help="explicit shard_map DDP engine (per-replica BN, "
                        "psum grad averaging) instead of GSPMD")
    p.add_argument("--fsdp", action="store_true",
                   help="FSDP/ZeRO-3: shard params + optimizer state over "
                        "the data axis (XLA inserts JIT all-gather / grad "
                        "reduce-scatter)")
    p.add_argument("--bucket-mb", type=int, default=0,
                   help="DDP gradient bucket size in MiB (0 = per-leaf psum)")
    p.add_argument("--allreduce", default="psum",
                   choices=["psum", "bucketed", "ring", "hierarchical"],
                   help="DDP gradient allreduce implementation "
                        "(hierarchical needs --dcn-data > 1)")
    p.add_argument("--image-size", default=32, type=int,
                   help="train/eval input resolution; when it differs from "
                        "the dataset's native size the batch is resized "
                        "on-device (224 = the reference finetune recipe)")
    p.add_argument("--no-augment", action="store_true")
    p.add_argument("--prefetch", default=2, type=int,
                   help="host prefetch depth (0 disables)")
    p.add_argument("--device-prefetch", default=2, type=int, metavar="N",
                   help="device-resident input prefetch: keep N batches' "
                        "sharded uploads in flight ahead of the running "
                        "step (0 = per-step device_put; "
                        "docs/PERFORMANCE.md)")
    p.add_argument("--grad-bucket-mb", default=None, type=float,
                   metavar="MB",
                   help="bucketed gradient allreduce cap (DDP only): "
                        "route grads through flat reverse-order buckets "
                        "overlapping the backward (the Reducer's "
                        "bucket_cap_mb; overrides --bucket-mb)")
    p.add_argument("--fused-opt", action="store_true",
                   help="fused Pallas SGD optimizer kernel "
                        "(ops/pallas_optim.py; sgd only, pure-XLA "
                        "fallback off-TPU)")
    p.add_argument("--native-loader", action="store_true",
                   help="assemble batches with the C++ row-gather")
    p.add_argument("--bf16", action="store_true", help="bfloat16 compute")
    p.add_argument("--num-devices", default=0, type=int,
                   help="data-parallel width (0 = all visible devices)")
    p.add_argument("--check-finite-every", default=0, type=int,
                   help="check drained metrics every sync and the params "
                        "every N steps for NaN/Inf (0 = off)")
    p.add_argument("--stall-budget", default=None, type=float, metavar="S",
                   help="arm the live stall watchdog around blocking syncs")
    p.add_argument("--consistency-every", default=0, type=int, metavar="N",
                   help="cross-replica consistency sentinel: every N steps "
                        "fingerprint params+opt state on device, compare "
                        "across the data axis, and repair a minority-bad "
                        "replica by re-broadcast (0 = off; "
                        "train/consistency.py)")
    p.add_argument("--barrier-timeout", default=None, type=float,
                   metavar="S",
                   help="hard bound (seconds) on each consistency check's "
                        "blocking ops — the multi-host rendezvous AND the "
                        "fingerprint fetch (any run) — so a wedged/missing "
                        "participant is reported as a straggler instead of "
                        "hanging")
    p.add_argument("--recovery-retries", default=0, type=int,
                   help="automatic recovery: restore the last good "
                        "checkpoint and retry the epoch on non-finite "
                        "detections, up to N times (0 = fail fast; needs "
                        "--check-finite-every)")
    p.add_argument("--recovery-lr-shrink", default=1.0, type=float,
                   help="multiply the LR by this factor on every "
                        "non-finite recovery (e.g. 0.5)")
    p.add_argument("--stall-exit", action="store_true",
                   help="escalate a stall-budget overrun to a graceful "
                        "checkpoint-and-exit")
    p.add_argument("--inject-faults", default=None, metavar="PLAN",
                   help="deterministic chaos plan, e.g. "
                        "'nan_loss@1,stall@0:0.5' (utils/faults.py)")
    p.add_argument("--dcn-data", default=1, type=int,
                   help="how many data-parallel ways cross the host (DCN) "
                        "boundary; must divide the data width. Lays the mesh "
                        "host-major so XLA reduces gradients hierarchically")
    p.add_argument("--log-name", default=None)
    return p.parse_args()


def main():
    args = parse_args()
    # Crash flight recorder (utils/flightrec.py): DMP_FLIGHT_RECORDER=
    # <dir> tees every telemetry record into a bounded ring and arms an
    # unhandled-exception hook that fsyncs the failure record, closes
    # the live streams, and dumps a postmortem bundle (ring tail +
    # all-thread stacks + span stacks + device memory + health scores).
    from distributed_model_parallel_tpu.utils import flightrec

    flightrec.install_from_env()
    best_effort_distributed_init()
    # First device contact, hardened (bench.py's bounded-retry pattern): a
    # permanently unreachable backend becomes one parseable JSON record +
    # exit 17, never a traceback (utils/device_contact.py).
    from distributed_model_parallel_tpu.utils.device_contact import (
        require_devices,
    )

    require_devices("train-data-parallel")
    import jax

    if args.ddp and args.fsdp:
        sys.exit("--ddp and --fsdp are mutually exclusive engines")
    if args.sync_bn and args.no_bn:
        sys.exit("--sync-bn and --no-bn are mutually exclusive")
    if args.sync_bn and args.model.endswith("_nobn"):
        sys.exit(f"--sync-bn conflicts with the BN-free model {args.model!r}")
    if not args.ddp and args.grad_bucket_mb is not None:
        sys.exit("--grad-bucket-mb routes gradients through bucketed_psum, "
                 "which needs the explicit DDP path; add --ddp")
    if not args.ddp and (args.allreduce != "psum" or args.bucket_mb):
        print("warning: --allreduce/--bucket-mb select the explicit DDP "
              "gradient transport; without --ddp the GSPMD path lets XLA "
              "insert the allreduce and these flags have no effect",
              file=sys.stderr)
    n = args.num_devices or len(jax.devices())
    steps_per_epoch = max(1, 50000 // args.batch_size)
    from distributed_model_parallel_tpu.config import RecoveryConfig
    from distributed_model_parallel_tpu.utils.faults import parse_faults

    recovery = RecoveryConfig(
        max_retries=args.recovery_retries,
        lr_shrink=args.recovery_lr_shrink,
        stall_exit=args.stall_exit,
        barrier_timeout_s=args.barrier_timeout,
        faults=parse_faults(args.inject_faults) if args.inject_faults
        else ())
    config = TrainConfig(
        model=ModelConfig(name=args.model,
                          batchnorm=("none" if args.no_bn
                                     else "sync" if args.sync_bn else "local"),
                          dtype="bfloat16" if args.bf16 else "float32"),
        data=DataConfig(name=args.dataset_type, root=args.data,
                        image_size=args.image_size,
                        batch_size=args.batch_size, num_workers=args.workers,
                        augment=not args.no_augment, prefetch=args.prefetch,
                        device_prefetch=args.device_prefetch,
                        use_native=args.native_loader),
        optimizer=OptimizerConfig(
            name=args.optimizer,
            learning_rate=args.lr, momentum=args.momentum,
            weight_decay=args.wd,
            warmup_steps=args.warmup_epochs * steps_per_epoch,
            accum_steps=args.accum_steps,
            ema_decay=args.ema_decay,
            fused=args.fused_opt),
        mesh=MeshConfig(data=n, dcn_data=args.dcn_data),
        epochs=args.epochs,
        resume=args.resume,
        emergency_every=args.emergency_every,
        elastic=args.elastic,
        async_checkpoint=args.async_checkpoint,
        device_resident_data=args.device_data,
        steps_per_dispatch=args.steps_per_dispatch,
        strategy="ddp" if args.ddp else ("fsdp" if args.fsdp else "gspmd"),
        ddp_bucket_bytes=args.bucket_mb * 1024 * 1024 or None,
        ddp_allreduce=args.allreduce,
        grad_bucket_mb=args.grad_bucket_mb,
        check_finite_every=args.check_finite_every,
        stall_budget_s=args.stall_budget,
        consistency_every=args.consistency_every,
        recovery=recovery,
        log_name=args.log_name or f"data_para_{args.batch_size}",
    )
    from distributed_model_parallel_tpu.train.trainer import Trainer
    trainer = Trainer(config)
    if args.profile:
        # XLA profiler trace (TensorBoard/Perfetto); use a short --epochs run
        # — the trace covers the whole fit.
        from distributed_model_parallel_tpu.utils.profiling import trace
        with trace(args.profile):
            trainer.fit()
    else:
        trainer.fit()


if __name__ == "__main__":
    main()
