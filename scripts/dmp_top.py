#!/usr/bin/env python
"""dmp_top: a live cockpit for a running fleet.

``dmp_report.py`` answers "what happened"; this answers "what is
happening". It live-tails one or more telemetry streams (rotation-safe
— ``utils/telemetry.StreamFollower`` follows a stream across its
``{stem}.N.jsonl`` rollovers) and/or polls a running process's
``/statusz`` exporter (``utils/statusz.py``), folds the records into a
fleet state, and renders a refreshing terminal view:

* one row per tenant/run: state, devices, global step, step rate,
  throughput, MFU (when the stream recorded FLOPs/step and the device
  has a peak-FLOPs table entry — honest ``-`` otherwise), loss, and
  recent fault/failure counts;
* the device-health line: quarantined devices and worst scores;
* firing alerts (typed ``alert`` records, utils/alerts.py) and recent
  postmortem bundles (``postmortem`` records, utils/flightrec.py);
* the serving engines' queue depth / page occupancy when a ``/statusz``
  endpoint is polled.

Usage:
  python scripts/dmp_top.py fleet/fleet.jsonl t0/log/t0.jsonl ...
  python scripts/dmp_top.py --statusz http://127.0.0.1:9200 log/lm.jsonl
  python scripts/dmp_top.py log/train.jsonl --once        # one frame (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.utils.metering import (  # noqa: E402
    LEDGER_BUCKETS,
)
from distributed_model_parallel_tpu.utils.telemetry import (  # noqa: E402
    RTRACE_TERMINAL_EVENTS,
    StreamFollower,
)


class FleetState:
    """Telemetry records + statusz polls folded into a render-ready
    fleet view. Pure state machine — deterministic under replay, so the
    tests drive it with canned records."""

    def __init__(self):
        self.tenants: dict[str, dict] = {}
        self.firing: dict[tuple[str, str], dict] = {}
        self.quarantined: set[int] = set()
        self.postmortems: list[str] = []
        self.statusz: dict | None = None
        self.last_ts: float = 0.0
        # Fleet serving (serve/fleet.py): live request migrations and
        # router assignment counts folded off the typed records.
        self.migrations: int = 0
        self.router_assignments: dict[str, int] = {}
        # Overload protection (serve/overload.py): typed shed counts by
        # reason, the live brownout level, and breaker states.
        self.shed_by_reason: dict[str, int] = {}
        self.brownout_level: int | None = None
        self.breaker_states: dict[str, str] = {}
        # Request tracing (utils/tracing.rtrace): live trace counts —
        # how many requests have a trace open vs. terminally accounted.
        self.rtrace_open: set[str] = set()
        self.rtrace_terminals: dict[str, int] = {}
        # Resource metering (utils/metering.py): live per-tenant cost
        # fold off the typed ``meter`` records, the fleet duty-cycle
        # fold off ``utilization`` records, and the last fleet
        # summary's metering rollup (source of goodput fractions —
        # meter records themselves carry cost, not SLO attainment).
        self.meter_tenants: dict[str, dict] = {}
        self.duty_s: dict[str, float] = {}
        self.metering_summary: dict | None = None
        # Untenanted streams (a plain trainer run) attribute their
        # records to the last run_start's run name.
        self._default_run = ""

    def _tenant(self, name: str) -> dict:
        return self.tenants.setdefault(name, {
            "state": "?", "devices": [], "step": 0, "step_time_s": None,
            "throughput": None, "unit": "", "loss": None, "faults": 0,
            "failures": 0, "mfu": None, "workload": "",
            "flops_per_step": None, "n_devices": None,
        })

    # -- ingest --------------------------------------------------------------
    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            self.last_ts = max(self.last_ts, ts)
        subject = str(rec.get("tenant") or rec.get("run")
                      or self._default_run)
        if kind == "run_start":
            subject = str(rec.get("tenant") or rec.get("run", "run"))
            self._default_run = subject
            t = self._tenant(subject)
            meta = rec.get("meta") or {}
            t["workload"] = meta.get("workload", t["workload"])
            t["flops_per_step"] = meta.get("model_flops_per_step")
            t["n_devices"] = (rec.get("device") or {}).get("n_devices")
            t["device_kind"] = (rec.get("device") or {}).get("device_kind")
            if t["state"] == "?":
                t["state"] = "running"
        elif kind == "step" and subject:
            t = self._tenant(subject)
            if rec.get("step") is not None:
                t["step"] = rec.get("step")
            if isinstance(rec.get("step_time_s"), (int, float)):
                t["step_time_s"] = rec["step_time_s"]
                self._refresh_mfu(t)
            for key, unit in (("tokens_per_s", "tok/s"),
                              ("samples_per_s", "smp/s")):
                if isinstance(rec.get(key), (int, float)):
                    t["throughput"], t["unit"] = rec[key], unit
            if isinstance(rec.get("loss"), (int, float)):
                t["loss"] = rec["loss"]
        elif kind == "tenant":
            t = self._tenant(str(rec.get("name")))
            t["state"] = str(rec.get("event", t["state"]))
            if rec.get("devices") is not None:
                t["devices"] = rec.get("devices")
            if rec.get("global_step") is not None:
                t["step"] = rec.get("global_step")
        elif kind == "fault" and subject:
            self._tenant(subject)["faults"] += 1
        elif kind == "failure" and subject:
            self._tenant(subject)["failures"] += 1
        elif kind == "health":
            for d in rec.get("devices") or []:
                if rec.get("event") == "quarantine":
                    self.quarantined.add(int(d))
                elif rec.get("event") == "reinstate":
                    self.quarantined.discard(int(d))
        elif kind == "alert":
            key = (str(rec.get("rule")), str(rec.get("subject")))
            if rec.get("state") == "firing":
                self.firing[key] = rec
            else:
                self.firing.pop(key, None)
        elif kind == "postmortem":
            self.postmortems.append(str(rec.get("bundle")))
        elif kind == "shed":
            reason = str(rec.get("reason"))
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1)
        elif kind == "brownout":
            if isinstance(rec.get("level"), int):
                self.brownout_level = rec["level"]
        elif kind == "breaker":
            self.breaker_states[str(rec.get("replica"))] = \
                str(rec.get("state"))
        elif kind == "migration":
            self.migrations += 1
        elif kind == "router":
            rep = str(rec.get("replica"))
            self.router_assignments[rep] = (
                self.router_assignments.get(rep, 0) + 1)
        elif kind == "rtrace":
            trace = str(rec.get("trace"))
            event = str(rec.get("event"))
            if event in RTRACE_TERMINAL_EVENTS:
                self.rtrace_open.discard(trace)
                self.rtrace_terminals[event] = (
                    self.rtrace_terminals.get(event, 0) + 1)
            else:
                self.rtrace_open.add(trace)
        elif kind == "meter":
            row = self.meter_tenants.setdefault(
                str(rec.get("tenant") or "-"),
                {"requests": 0, "tokens": 0, "sheds": 0,
                 "chip_s": 0.0, "hops": 0})
            row["chip_s"] += rec.get("chip_s") or 0.0
            event = str(rec.get("event"))
            if event == "hop":
                row["hops"] += 1
            else:
                row["requests"] += 1
                row["tokens"] += rec.get("tokens") or 0
                if event in ("shed", "expired"):
                    row["sheds"] += 1
        elif kind == "utilization":
            for b in LEDGER_BUCKETS:
                self.duty_s[b] = (self.duty_s.get(b, 0.0)
                                  + (rec.get(f"{b}_s") or 0.0))
        elif (kind == "serve" and rec.get("event") == "summary"
                and rec.get("metering")):
            self.metering_summary = rec["metering"]

    def _refresh_mfu(self, t: dict) -> None:
        """MFU from stream data alone: FLOPs/step / n_devices /
        step_time / chip peak — None (rendered ``-``) whenever any
        factor is missing (CPU has no peak entry; CNN streams record no
        FLOPs). Same honesty rule as the report."""
        try:
            from distributed_model_parallel_tpu.utils.profiling import (
                TPU_PEAK_FLOPS,
                match_device_kind,
            )

            peak = match_device_kind(TPU_PEAK_FLOPS,
                                     kind=t.get("device_kind") or "")
            flops, n = t.get("flops_per_step"), t.get("n_devices")
            if peak and flops and n and t["step_time_s"]:
                t["mfu"] = flops / n / t["step_time_s"] / peak
        except Exception:
            pass

    def poll_statusz(self, url: str) -> None:
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/statusz",
                                        timeout=2) as resp:
                self.statusz = json.load(resp)
        except Exception as e:
            self.statusz = {"error": f"{type(e).__name__}: {e}"}
            return
        health = self.statusz.get("health") or {}
        for d in health.get("quarantined") or []:
            self.quarantined.add(int(d))

    # -- render --------------------------------------------------------------
    def render(self) -> str:
        lines = []
        firing = sorted(self.firing)
        head = (f"dmp_top  {len(self.tenants)} runs  "
                f"quarantined={sorted(self.quarantined) or '[]'}  "
                f"alerts={'NONE' if not firing else len(firing)}")
        lines.append(head)
        lines.append("-" * max(72, len(head)))
        lines.append(f"{'run':<14}{'state':<20}{'step':>7}{'rate':>10}"
                     f"{'thruput':>14}{'MFU':>7}{'loss':>9}"
                     f"{'faults':>7}{'fail':>6}  devices")
        for name, t in sorted(self.tenants.items()):
            rate = (f"{1.0 / t['step_time_s']:.1f}/s"
                    if t.get("step_time_s") else "-")
            thr = (f"{t['throughput']:,.0f} {t['unit']}"
                   if t.get("throughput") is not None else "-")
            mfu = f"{t['mfu']:.3f}" if t.get("mfu") is not None else "-"
            loss = (f"{t['loss']:.4g}" if t.get("loss") is not None
                    else "-")
            lines.append(
                f"{name[:13]:<14}{t['state'][:19]:<20}{t['step']:>7}"
                f"{rate:>10}{thr:>14}{mfu:>7}{loss:>9}"
                f"{t['faults']:>7}{t['failures']:>6}  {t['devices']}")
        for key in firing:
            rec = self.firing[key]
            lines.append(f"ALERT firing  {key[0]}"
                         + (f"[{key[1]}]" if key[1] else "")
                         + f"  value={rec.get('value')} "
                           f"threshold={rec.get('threshold')}")
        for p in self.postmortems[-3:]:
            lines.append(f"POSTMORTEM  {p}")
        if self.migrations or self.router_assignments:
            lines.append(
                f"fleet serving  migrations={self.migrations}  router="
                + (" ".join(f"{k}:{v}" for k, v in
                            sorted(self.router_assignments.items()))
                   or "-"))
        if (self.shed_by_reason or self.brownout_level
                or self.breaker_states):
            shed = (" ".join(f"{k}:{v}" for k, v in
                             sorted(self.shed_by_reason.items())) or "-")
            brk = (" ".join(f"{k}:{v}" for k, v in
                            sorted(self.breaker_states.items())) or "-")
            level = (self.brownout_level
                     if self.brownout_level is not None else "-")
            lines.append(f"overload  shed={shed}  brownout={level}  "
                         f"breaker={brk}")
        if self.rtrace_open or self.rtrace_terminals:
            terms = (" ".join(f"{k}:{v}" for k, v in
                              sorted(self.rtrace_terminals.items())) or "-")
            lines.append(f"traces  open={len(self.rtrace_open)}  "
                         f"terminal={terms}")
        if any(self.duty_s.values()):
            wall = sum(self.duty_s.values())
            lines.append("utilization  " + "  ".join(
                f"{b}={self.duty_s.get(b, 0.0) / wall:.0%}"
                for b in LEDGER_BUCKETS) + f"  wall={wall:.1f}s")
        summary_tenants = ((self.metering_summary or {}).get("by_tenant")
                           or {})
        for name, row in sorted(self.meter_tenants.items()):
            gf = (summary_tenants.get(name) or {}).get("goodput_fraction")
            lines.append(
                f"tenant {name[:12]:<13} req={row['requests']}"
                f"  chip={row['chip_s']:.3f}s  tokens={row['tokens']}"
                f"  goodput="
                + (f"{gf:.0%}" if isinstance(gf, (int, float)) else "-")
                + f"  sheds={row['sheds']}  hops={row['hops']}")
        if self.statusz is not None:
            if "error" in self.statusz:
                lines.append(f"statusz: {self.statusz['error']}")
            else:
                for name, prov in sorted(
                        (self.statusz.get("providers") or {}).items()):
                    if prov.get("workload") == "serve-fleet":
                        # The fleet provider: one header plus a row per
                        # replica (state, queue depth, page occupancy,
                        # router assignment counts).
                        lines.append(
                            f"fleet[{name}]  "
                            f"{len(prov.get('live') or [])}"
                            f"/{prov.get('n_replicas')} live"
                            f"  pending={prov.get('pending')}"
                            f"  migrations={prov.get('migrations')}"
                            f"  kills={prov.get('replica_kills')}"
                            + (f"  shed={prov.get('requests_shed')}"
                               if prov.get("requests_shed") else ""))
                        # Per-cell rollup (serve/cells.py): liveness,
                        # reachability, aggregated breaker state.
                        for cname, cell in sorted(
                                (prov.get("cells") or {}).items()):
                            lines.append(
                                f"  cell {cname}  "
                                f"{len(cell.get('live') or [])}"
                                f"/{len(cell.get('members') or [])} live"
                                f"  routed={cell.get('assignments')}"
                                + ("  PARTITIONED"
                                   if cell.get("partitioned") else "")
                                + (f"  brk={cell.get('breaker')}"
                                   if cell.get("breaker") not in
                                   (None, "closed") else ""))
                        for rname, rep in sorted(
                                (prov.get("replicas") or {}).items()):
                            occ = rep.get("page_occupancy")
                            lines.append(
                                f"  replica {rname}  "
                                f"{str(rep.get('state')):<12}"
                                f"queue={rep.get('queue_depth')}"
                                f"  active={rep.get('active_requests')}"
                                + (f"  pages={occ:.2f}"
                                   if isinstance(occ, (int, float))
                                   else "")
                                + f"  routed={rep.get('assignments')}"
                                + (f"  brk={rep.get('breaker')}"
                                   if rep.get("breaker") not in
                                   (None, "closed") else "")
                                + f"  devices={rep.get('devices')}")
                        continue
                    if prov.get("workload") == "serve":
                        line = (
                            f"serve[{name}]  queue={prov.get('queue_depth')}"
                            f"  active={prov.get('active_requests')}"
                            f"/{prov.get('n_slots')} slots"
                            f"  pages={prov.get('page_occupancy'):.2f}"
                            if isinstance(prov.get("page_occupancy"),
                                          (int, float))
                            else f"serve[{name}]  "
                                 f"queue={prov.get('queue_depth')}")
                        # live prefix-cache + spec-decode health
                        hit = prov.get("cache_hit_rate")
                        if prov.get("prefix_cache"):
                            line += (f"  hit={hit:.2f}" if isinstance(
                                hit, (int, float)) else "  hit=-")
                            line += f"  shared={prov.get('shared_pages')}"
                        acc = prov.get("draft_accept_rate")
                        if prov.get("spec_k"):
                            line += (f"  accept={acc:.2f}" if isinstance(
                                acc, (int, float)) else "  accept=-")
                        # live overload state (shed counts, brownout)
                        if prov.get("requests_shed"):
                            line += (f"  shed={prov.get('requests_shed')}"
                                     f" (rej "
                                     f"{prov.get('requests_rejected')})")
                        if prov.get("brownout_level") is not None:
                            line += f"  bo={prov.get('brownout_level')}"
                        lines.append(line)
                spans = self.statusz.get("spans") or {}
                for thread, stack in sorted(spans.items()):
                    lines.append(f"span  {thread}: {' > '.join(stack)}")
        return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="Live fleet cockpit over telemetry streams and/or a "
                    "/statusz exporter")
    p.add_argument("jsonl", nargs="*",
                   help="telemetry stream(s) to live-tail (the fleet "
                        "stream plus per-tenant streams; rotation-safe)")
    p.add_argument("--statusz", default=None, metavar="URL",
                   help="poll this exporter's /statusz each frame "
                        "(e.g. http://127.0.0.1:9200)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval seconds")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (CI / scripting)")
    p.add_argument("--frames", type=int, default=None,
                   help="exit after N frames")
    args = p.parse_args(argv)
    if not args.jsonl and not args.statusz:
        raise SystemExit("give at least one stream or --statusz URL")
    state = FleetState()
    followers = [StreamFollower(path) for path in args.jsonl]
    frame = 0
    while True:
        for f in followers:
            for rec in f.poll():
                state.observe(rec)
        if args.statusz:
            state.poll_statusz(args.statusz)
        out = state.render()
        if args.once or args.frames is not None:
            print(out, flush=True)
        else:
            # Full-screen refresh: clear + home, like top(1).
            print("\x1b[2J\x1b[H" + out, flush=True)
        frame += 1
        if args.once or (args.frames is not None and frame >= args.frames):
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
