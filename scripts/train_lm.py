#!/usr/bin/env python
"""Transformer LM training driver over a dp x pp x tp x sp mesh.

Example (8 virtual CPU devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/train_lm.py --dp 2 --pp 2 --tp 2 --layers 4 --steps 20
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from scripts._cpu_devices import force_cpu_devices

force_cpu_devices(("--dp", "--pp", "--tp", "--sp", "--ep"))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel ways (shards --moe-experts)")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="experts per block (0 = dense FFN)")
    p.add_argument("--moe-top-k", type=int, default=2)
    p.add_argument("--moe-z-weight", type=float, default=0.0,
                   help="router z-loss weight (ST-MoE logit-drift "
                        "regularizer; 0 = off)")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings instead of a learned "
                        "table (relative positions; extrapolates)")
    p.add_argument("--attn-window", type=int, default=None,
                   help="sliding-window attention width (flash kernels, "
                        "O(T*W) compute); incompatible with --sp")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: k/v head count (must "
                        "divide --heads; 1 = multi-query). Shrinks the "
                        "decode KV cache by heads/kv-heads")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (activation recompute — "
                        "the long-context memory lever)")
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "dots"],
                   help="with --remat: 'dots' saves matmul outputs and "
                        "recomputes only elementwise ops (less recompute, "
                        "slightly more HBM)")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help="chunked cross-entropy head: compute logits in "
                        "N-token slices so [B, T, vocab] never "
                        "materializes — the head-side long-context memory "
                        "lever (0 = dense head)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--microbatches", type=int, default=1)
    p.add_argument("--schedule", default="gpipe", choices=["gpipe", "1f1b"],
                   help="SPMD pipeline schedule: gpipe holds all "
                        "microbatches' activations through the backward; "
                        "1f1b interleaves forward/backward so peak "
                        "activation memory is bounded by the stage count "
                        "(benchmarks/pipeline_memory.json)")
    p.add_argument("--virtual-stages", type=int, default=1,
                   help="Megatron interleaved virtual stages for the 1f1b "
                        "schedule (device s owns V model chunks; bubble "
                        "shrinks ~V-fold; microbatches must divide by the "
                        "stage count)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--emergency-every", default=0, type=int, metavar="N",
                   help="elastic resume: write the emergency checkpoint "
                        "slot (exact mid-epoch resume state) every N steps "
                        "(0 = only the preemption save; train/elastic.py)")
    p.add_argument("--elastic", action="store_true",
                   help="on startup, shrink the data axis to the largest "
                        "degree the live device count and batch size allow "
                        "and reshard the resumed checkpoint onto the "
                        "rebuilt mesh")
    p.add_argument("--check-finite-every", default=0, type=int,
                   help="check loss every step and params every N steps "
                        "for NaN/Inf (0 = off)")
    p.add_argument("--consistency-every", default=0, type=int, metavar="N",
                   help="cross-replica consistency sentinel: every N steps "
                        "fingerprint params+opt state on device, compare "
                        "across the dp axis, and repair a minority-bad "
                        "replica by re-broadcast (0 = off; "
                        "train/consistency.py)")
    p.add_argument("--barrier-timeout", default=None, type=float,
                   metavar="S",
                   help="hard bound (seconds) on each consistency check's "
                        "blocking ops — the multi-host rendezvous AND the "
                        "fingerprint fetch (any run) — so a wedged/missing "
                        "participant is reported as a straggler instead of "
                        "hanging")
    p.add_argument("--recovery-retries", default=0, type=int,
                   help="restore the last good checkpoint and retry the "
                        "epoch on non-finite detections, up to N times")
    p.add_argument("--recovery-lr-shrink", default=1.0, type=float,
                   help="multiply the LR by this factor on every recovery")
    p.add_argument("--inject-faults", default=None, metavar="PLAN",
                   help="deterministic chaos plan, e.g. 'nan_loss@3' "
                        "(utils/faults.py)")
    return p.parse_args()


def main():
    args = parse_args()
    # Crash flight recorder opt-in (utils/flightrec.py): ring tee +
    # unhandled-exception postmortem hook under DMP_FLIGHT_RECORDER.
    from distributed_model_parallel_tpu.utils import flightrec

    flightrec.install_from_env()
    # First device contact, hardened (bench.py's bounded-retry pattern):
    # an unreachable backend becomes one parseable JSON record + exit 17.
    from distributed_model_parallel_tpu.utils.device_contact import (
        require_devices,
    )

    require_devices("train-lm")
    from distributed_model_parallel_tpu.config import (
        MeshConfig,
        OptimizerConfig,
        RecoveryConfig,
    )
    from distributed_model_parallel_tpu.utils.faults import parse_faults
    from distributed_model_parallel_tpu.models.transformer import TransformerConfig
    from distributed_model_parallel_tpu.train.lm_trainer import (
        LMTrainConfig,
        LMTrainer,
    )

    if args.layers % max(args.pp, 1):
        raise SystemExit("--layers must be divisible by --pp")
    if args.ep > 1 and args.moe_experts % args.ep:
        raise SystemExit("--moe-experts must be divisible by --ep")
    if args.moe_experts and not (1 <= args.moe_top_k <= args.moe_experts):
        raise SystemExit(
            f"--moe-top-k must be in [1, --moe-experts={args.moe_experts}]")
    if args.attn_window is not None and args.attn_window < 1:
        raise SystemExit("--attn-window must be >= 1")
    config = LMTrainConfig(
        model=TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.heads,
            n_layers=args.layers, d_ff=args.d_ff,
            max_seq_len=max(args.seq_len, 128),
            tp_axis="model" if args.tp > 1 else None,
            sp_axis="seq" if args.sp > 1 else None,
            moe_experts=args.moe_experts, moe_top_k=args.moe_top_k,
            moe_z_weight=args.moe_z_weight,
            ep_axis="expert" if args.ep > 1 else None,
            pos_embedding="rope" if args.rope else "learned",
            n_kv_heads=args.kv_heads,
            attn_window=args.attn_window,
            remat=args.remat, remat_policy=args.remat_policy,
            loss_chunk=args.loss_chunk,
            attn_impl="flash" if args.attn_window is not None else "auto"),
        mesh=MeshConfig(data=args.dp, stage=args.pp, model=args.tp,
                        seq=args.sp, expert=args.ep),
        optimizer=OptimizerConfig(learning_rate=args.lr, weight_decay=0.0,
                                  warmup_steps=10),
        batch_size=args.batch_size, seq_len=args.seq_len,
        num_microbatches=args.microbatches,
        pipeline_schedule=args.schedule,
        virtual_stages=args.virtual_stages,
        steps_per_epoch=args.steps, epochs=args.epochs, resume=args.resume,
        emergency_every=args.emergency_every, elastic=args.elastic,
        check_finite_every=args.check_finite_every,
        consistency_every=args.consistency_every,
        recovery=RecoveryConfig(
            max_retries=args.recovery_retries,
            lr_shrink=args.recovery_lr_shrink,
            barrier_timeout_s=args.barrier_timeout,
            faults=parse_faults(args.inject_faults) if args.inject_faults
            else ()),
    )
    LMTrainer(config).fit()


if __name__ == "__main__":
    main()
