#!/usr/bin/env python
"""Pipeline (model-parallel) training driver.

CLI parity with the reference's ``model_parallel.py`` (``:15-42``) with the
mesh replacing ``--dist-url``/``--dist-backend``/``--world-size`` +
``mp.spawn`` (SURVEY.md §2.4): ``--stages`` is the pipeline depth,
``--microbatches 1`` reproduces the reference's naive 1-batch-in-flight
schedule, larger values give GPipe. Stage boundaries are configurable data
(``--boundaries 0,4,10,16,19`` = the reference's hard-coded 4-GPU split,
``model_parallel.py:102-144``), not per-rank code.

Example:
  python scripts/train_model_parallel.py --stages 4 --batch-size 512 --lr 0.4
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts._cpu_devices import force_cpu_devices

force_cpu_devices((("--stages", "--world-size"), "--dp"))

from distributed_model_parallel_tpu.config import (
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainConfig,
)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("data", nargs="?", default="./data")
    p.add_argument("--dataset-type", "-type", default="cifar10",
                   choices=["cifar10", "imagenet", "cub200", "place365",
                            "synthetic"])
    p.add_argument("--model", default="mobilenetv2")
    p.add_argument("--stages", "--world-size", default=4, type=int)
    p.add_argument("--microbatches", default=1, type=int,
                   help="1 = reference's naive schedule; >1 = GPipe/1F1B")
    p.add_argument("--schedule", default="gpipe", choices=["gpipe", "1f1b"])
    p.add_argument("--virtual-stages", default=1, type=int,
                   help=">1 = Megatron interleaved placement: each device "
                        "owns that many non-contiguous layer chunks")
    p.add_argument("--boundaries", default=None,
                   help="comma-separated unit boundaries, e.g. 0,4,10,16,19")
    p.add_argument("--auto-partition", action="store_true",
                   help="choose boundaries by minimax over XLA per-unit "
                        "FLOPs instead of equal unit counts")
    p.add_argument("--lr", default=0.4, type=float)
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--wd", default=1e-4, type=float)
    p.add_argument("--epochs", default=100, type=int)
    p.add_argument("--batch-size", "-b", default=512, type=int)
    p.add_argument("--warmup-epochs", default=10, type=int)
    p.add_argument("--resume", "-r", action="store_true")
    p.add_argument("--emergency-every", default=0, type=int, metavar="N",
                   help="elastic resume: write the emergency checkpoint "
                        "slot (exact mid-epoch resume state) every N steps "
                        "(0 = only the preemption save; train/elastic.py)")
    p.add_argument("--image-size", default=32, type=int,
                   help="train/eval input resolution; when it differs from "
                        "the dataset's native size the batch is resized "
                        "on-device (224 = the reference finetune recipe)")
    p.add_argument("--no-augment", action="store_true")
    p.add_argument("--log-name", default=None)
    p.add_argument("--engine", default="runner", choices=["runner", "spmd"],
                   help="'runner' = single-controller PipelineRunner (one "
                        "program per stage, schedules incl. 1F1B/virtual "
                        "stages); 'spmd' = single-program shard_map+ppermute "
                        "pipeline over a data x stage mesh "
                        "(parallel/spmd_cnn_pipeline.py) — the multi-host "
                        "path; --dp sets its data-parallel width")
    p.add_argument("--dp", default=1, type=int,
                   help="data-axis width for --engine spmd (total devices "
                        "= dp * stages)")
    return p.parse_args()


def main():
    args = parse_args()
    # Crash flight recorder opt-in (utils/flightrec.py): ring tee +
    # unhandled-exception postmortem hook under DMP_FLIGHT_RECORDER.
    from distributed_model_parallel_tpu.utils import flightrec

    flightrec.install_from_env()
    # First device contact, hardened (bench.py's bounded-retry pattern):
    # an unreachable backend becomes one parseable JSON record + exit 17.
    from distributed_model_parallel_tpu.utils.device_contact import (
        require_devices,
    )

    require_devices("train-model-parallel")
    boundaries = (None if args.boundaries is None else
                  [int(x) for x in args.boundaries.split(",")])
    if boundaries is not None and args.auto_partition:
        print("warning: explicit --boundaries override --auto-partition",
              file=sys.stderr)
    steps_per_epoch = max(1, 50000 // args.batch_size)
    config = TrainConfig(
        model=ModelConfig(name=args.model),
        data=DataConfig(name=args.dataset_type, root=args.data,
                        image_size=args.image_size,
                        batch_size=args.batch_size,
                        augment=not args.no_augment),
        optimizer=OptimizerConfig(
            learning_rate=args.lr, momentum=args.momentum,
            weight_decay=args.wd,
            warmup_steps=args.warmup_epochs * steps_per_epoch),
        mesh=MeshConfig(data=args.dp, stage=args.stages),
        epochs=args.epochs,
        resume=args.resume,
        emergency_every=args.emergency_every,
        strategy=("spmd_pipeline" if args.engine == "spmd" else "gspmd"),
        num_microbatches=args.microbatches,
        stage_boundaries=boundaries,
        auto_partition=args.auto_partition,
        pipeline_schedule=args.schedule,
        virtual_stages=args.virtual_stages,
        log_name=args.log_name or f"{args.batch_size}",
    )
    if args.engine == "runner" and args.dp != 1:
        raise SystemExit(
            "--dp is an --engine spmd knob; the single-controller runner "
            "pipelines over stages only (PipelineTrainer ignores the data "
            "axis — refusing to silently drop your requested data "
            "parallelism)")
    if args.engine == "spmd":
        if args.virtual_stages != 1:
            raise SystemExit(
                "--engine spmd runs one stage per device; virtual stages "
                "are a runner-engine schedule (interleaving only beats "
                "GPipe under 1F1B ordering, and the SPMD 1F1B is "
                "single-level — see docs/ROUND4.md)")
        from distributed_model_parallel_tpu.train.trainer import Trainer

        Trainer(config).fit()
        return
    from distributed_model_parallel_tpu.train.pipeline_trainer import (
        PipelineTrainer,
    )
    PipelineTrainer(config).fit()


if __name__ == "__main__":
    main()
